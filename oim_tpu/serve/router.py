"""Serving router: registry-discovered load balancing over oim-serve.

The reference's central routing idea — clients address components by ID
through the registry, never by network address
(/root/reference/pkg/oim-registry/registry.go:162-189) — applied to the
inference data plane: N ``oim-serve`` backends self-register
``serve/<id>/address`` keys (the controller heartbeat pattern,
/root/reference/pkg/oim-controller/controller.go:425-443), and this
router discovers them by prefix query, health-checks them, and
least-active balances the HTTP serving API across them.

Scope: the router is a *dispatcher*, not a batch merger — each request
runs wholly on one backend (continuous batching happens inside the
backend engine).  That keeps the router stateless and restartable, the
same property the reference's transparent proxy has.

Behavior:
- Balancing: least active in-flight requests among healthy backends
  (ties broken round-robin).  Generate requests sharing a long prompt
  prefix prefer one rendezvous-hashed backend (whose prefix cache
  holds that prefix) unless it is overloaded — cache locality without
  hot-prefix starvation.
- Fleet prefix residency (doc/serving.md "Fleet prefix residency"):
  backends advertise a capped summary of their RESIDENT prefix-cache
  entries (content digest + covered tokens) through the per-tick
  ``/v1/info`` load refetch; token-list generate traffic then routes
  to the backend whose digest set covers the longest prompt prefix
  (the rendezvous affinity's load-slack guard kept — residency-AWARE,
  not residency-blind).  On a miss where a sibling holds the
  best-covering digest, the router ships that entry sibling→target
  first (``GET /v1/kv?prefix=`` → ``PUT /v1/kv``) so the target
  aliases the fetched blocks instead of recomputing the prefill;
  every ship failure falls back to recompute — token-identical either
  way, a ship can slow a request but never fail it.
- Health: GET /healthz per backend on an interval; a backend is out
  after ``unhealthy_after`` consecutive failures and back on the first
  success.  A request-level connection failure counts too, so a dead
  backend stops receiving traffic immediately, not at the next probe.
- Retry & failover: a backend that fails at the CONNECTION level — or
  dies mid-response — is excluded for the REQUEST'S LIFETIME and the
  work moves to another healthy backend (bounded: each backend is
  tried at most once per request).  Non-stream responses are buffered
  before forwarding, so a backend death mid-body resubmits the whole
  request with zero client-visible damage (generation is deterministic
  by seed, so the re-run answers identically).  An HTTP-level error
  from a backend that answered passes through verbatim (with its
  Retry-After header, when present).
- Stream-splice failover: for NDJSON ``/v1/generate`` streams with a
  token-list prompt, the router records the tokens each backend has
  emitted; when the backend dies mid-stream (EOF before a terminal
  done/error line), it resubmits to another healthy backend as
  prompt + emitted-tokens continuation and SPLICES the remainder into
  the same client stream — token-identical for greedy decoding (the
  engine's exactness invariant: a continuation prefill reproduces the
  original KV bit-for-bit), best-effort for sampled requests (the
  continuation's PRNG key indices restart relative to the new prompt;
  still deterministic given the fault point, documented in
  doc/operations.md "Serving failure modes").  Text-prompt streams and
  SSE completions streams cannot be spliced (the router has no
  tokenizer to rebuild the prompt) and keep the old
  bytes-already-with-the-client → terminal-error behavior.
- Streaming: NDJSON bodies are piped through chunk-by-chunk; only
  complete lines are forwarded, so a mid-line backend death never
  corrupts client framing.
- Disaggregated prefill/decode (serve/disagg.py, ``--disagg-prompt-
  tokens``): when the fleet is partitioned into pools (oim-serve
  ``--pool prefill|decode``), spliceable generate streams whose prompt
  reaches the threshold run their prefill on a prefill-pool backend
  (``max_new_tokens`` clamped to the first chunk, KV retained), the
  written KV ships as paged blocks to a decode-pool backend
  (``GET /v1/kv`` → ``PUT /v1/kv``), and the stream continues there —
  TTFT stops queueing behind decode chunks and the two phases scale
  independently.  Every failure along the ship falls back to the
  splice-recompute continuation above: token-identical greedy, just
  paying the prefill again.  Regular traffic avoids prefill-pool
  backends while any non-prefill backend is healthy.

Endpoints: the serving API (POST /v1/generate, /v1/beam, /v1/embed,
and the OpenAI-compatible /v1/completions) proxied; GET /healthz (ok while ≥1 backend is healthy), /v1/stats
(router counters + per-backend state), /v1/requests (fleet-merged
completed-request forensics: every backend's /debugz/requests ring,
entries stamped with their backend id — `oimctl requests` reads this),
/debugz (the router's own flight-recorder rings), /metrics (Prometheus).

Tracing: every proxied request gets a router span (parented on the
client's ``traceparent`` when present), and every attempt — original
and failover alike — forwards the ROUTER span's context, so all server
spans and the engine phase spans below them share one trace id
(`oimctl trace` renders router→server→engine as one tree, spliced
failovers included).
"""

from __future__ import annotations

import hashlib
import http.client
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from concurrent import futures
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from oim_tpu import log
from oim_tpu.common import events, metrics, tracing
from oim_tpu.common import locksan
from oim_tpu.qos.policy import DEFAULT_POLICY as _QOS_DEFAULT
from oim_tpu.serve.disagg import (
    prefix_digest,
    release_kv,
    release_slot,
    ship_kv,
    ship_prefix,
    ship_slot,
)
from oim_tpu.serve.httptls import check_serving_peer, peer_common_name

PROXIED = (
    "/v1/generate",
    "/v1/beam",
    "/v1/embed",
    "/v1/completions",
    "/v1/chat/completions",
)

# Per-tenant QoS state rows the router keeps (token buckets + throttle
# counters).  Tenant names are client-influenced (the x-oim-tenant
# header on a plain-HTTP perimeter), so the table is capped: at the
# limit the longest-idle row is dropped — its bucket restarts full,
# which errs toward admitting, never toward wedging a tenant out.
_MAX_TENANT_ROWS = 256

# Prefix demote-to-peer on drain (ROADMAP item 5, ISSUE 17): how many
# of a draining backend's hottest exportable prefix entries the router
# ships to a sibling before teardown destroys its cache working set.
# Small on purpose — demotion races the drain grace, and the hottest
# handful carries most of the fleet's hit rate.
DRAIN_DEMOTE_ENTRIES = 4


@dataclass
class Backend:
    """One oim-serve instance as the router sees it."""

    id: str
    url: str  # http://host:port, no trailing slash
    from_registry: bool = False
    healthy: bool = True
    active: int = 0
    completed: int = 0
    fails: int = 0  # consecutive health/connection failures
    # From the backend's /v1/info (fetched once at the first successful
    # probe): whether its engine runs a prompt-prefix cache.  Affinity
    # routing only applies to cache-running backends — pinning a hot
    # prefix to one backend is pure load skew if nothing caches it.
    prefix_cache: bool = False
    # Also from /v1/info: the engine's decode pipeline depth (2 =
    # dispatch-ahead double buffering).  Surfaced in the router's
    # /v1/stats so a fleet operator can spot a replica accidentally
    # running serial (pipeline_depth 1) — roughly a 2x throughput skew
    # on tunneled deployments — without curling every backend.
    pipeline_depth: int = 0
    # Disaggregation pool role (/v1/info "pool", oim-serve --pool):
    # "prefill" backends take long-prompt admissions and serve /v1/kv
    # exports, "decode" backends ingest shipped KV and stream the
    # continuation, "mixed" (the default) stays outside the ship path.
    # Regular traffic avoids prefill-pool backends whenever any
    # non-prefill backend is healthy (the partition's whole point:
    # TTFT work must not queue behind decode chunks, and vice versa).
    pool: str = "mixed"
    info_fetched: bool = False
    # The backend's live load snapshot (the /v1/info "load" section =
    # its load/<cn> registry value), refreshed every successful health
    # probe — queue depth, busy/total slots, token rate, shed counters,
    # brownout.  Surfaced per backend in the router's /v1/stats so an
    # operator (or the autoscaler runbook's incident queries) sees the
    # whole fleet's pressure from one endpoint.
    load: dict = field(default_factory=dict)
    # Latched once per drain (ISSUE 17): the prefix demote-to-peer
    # sweep ran for this backend's current draining episode.  Reset
    # when the load flag clears (restart), so a re-drain demotes again.
    drain_demoted: bool = False


class _SpliceState:
    """Failover state for one spliceable NDJSON generate stream.

    Spliceable = ``POST /v1/generate`` with ``"stream": true`` and a
    token-list prompt: the router can then rebuild the prompt for a
    continuation (prompt + tokens already emitted) without a tokenizer.
    ``prior_tokens``/``prior_lps`` hold what DEAD backends emitted;
    the live attempt's tokens ride local lists and fold in only on
    death, so the terminal done line (whose ``tokens`` field is the
    serving backend's own full generation) is never double-counted."""

    def __init__(self, payload: dict, body: bytes):
        self.payload = payload
        self._orig_body = body
        self.t0 = time.monotonic()  # for continuation deadline_ms decay
        self.orig_tokens = [int(t) for t in payload["tokens"]]
        # Mirrors the server-side default (server.py _generate).
        self.orig_max_new = int(payload.get("max_new_tokens", 16))
        self.eos_id = payload.get("eos_id")
        self.stop_ids = {int(t) for t in payload.get("stop_ids", ())}
        self.want_logprobs = bool(payload.get("logprobs"))
        self.prior_tokens: list[int] = []
        self.prior_lps: list[float] = []
        self.started = False  # response headers sent to our client
        # The disaggregation path's captured terminal line: a prefill
        # leg's done object (suppressed from the client — the stream
        # continues on a decode backend), carrying the request_id that
        # addresses the held KV.
        self.captured_done: dict | None = None
        # Live slot migration (ISSUE 17): a migrate marker line sets
        # the rid addressing the suspended slot (GET /v1/slot?rid=)
        # and _pipe_spliced records which backend suspended it.
        self.migrate_rid: int | None = None
        self.migrate_src: "Backend | None" = None

    @staticmethod
    def plan(path: str, body: bytes | None) -> "_SpliceState | None":
        """A state when this request is spliceable, else None (any
        parse problem means no splice — never an error)."""
        if path != "/v1/generate" or not body:
            return None
        try:
            payload = json.loads(body)
            if not payload.get("stream"):
                return None
            tokens = payload.get("tokens")
            if not isinstance(tokens, list) or not tokens:
                return None
            return _SpliceState(payload, body)
        except Exception:
            return None

    def prefill_body(self, first_tokens: int) -> bytes:
        """The disaggregation prefill leg's body: the original request
        with ``max_new_tokens`` clamped to the first chunk and
        ``hold_kv`` set — the backend retains the written KV for the
        ship instead of freeing it (doc/serving.md "Disaggregated
        prefill/decode")."""
        payload = dict(self.payload)
        payload["max_new_tokens"] = min(self.orig_max_new, first_tokens)
        payload["hold_kv"] = True
        return json.dumps(payload).encode()

    def request_body(self, extra: dict | None = None) -> bytes:
        """The next attempt's body: the original bytes verbatim until a
        failover, then prompt + emitted-tokens continuation with the
        budget reduced by what the client already has.  ``cache_prefix``
        is dropped from continuations (a one-off spliced prompt must
        not evict real entries from the new backend's prefix cache).
        ``extra`` fields (the disaggregation/migration paths'
        ``kv_import``) merge into a continuation body — and force the
        continuation form even with nothing emitted yet, so a slot
        migrated before its first token still resumes from shipped KV
        instead of resubmitting the original body sans import."""
        if not self.prior_tokens and not extra:
            return self._orig_body
        payload = dict(self.payload)
        payload["tokens"] = self.orig_tokens + self.prior_tokens
        payload["max_new_tokens"] = (
            self.orig_max_new - len(self.prior_tokens)
        )
        # Global emission index of the continuation's first sampled
        # token (ISSUE 17): keeps per-position PRNG keys identical to
        # an undisturbed solo run, so SAMPLED continuations are exact
        # like greedy ones (a no-op for greedy decode).
        payload["sample_base"] = (
            int(payload.get("sample_base") or 0) + len(self.prior_tokens)
        )
        payload.pop("cache_prefix", None)
        payload.pop("hold_kv", None)
        if extra:
            payload.update(extra)
        try:
            ms = float(payload.get("deadline_ms", 0))
            if ms > 0:
                # The continuation inherits only the REMAINING budget —
                # a failover must not restart the client's deadline.
                elapsed_ms = (time.monotonic() - self.t0) * 1000.0
                payload["deadline_ms"] = max(1, int(ms - elapsed_ms))
        except (TypeError, ValueError):
            pass
        return json.dumps(payload).encode()

    def finished(self) -> str | None:
        """Non-None when the emitted prefix already ended the request
        (budget exhausted / EOS / stop token emitted) — there is
        nothing left to decode, so the final line can be synthesized
        locally instead of resubmitting a zero-token continuation."""
        if len(self.prior_tokens) >= self.orig_max_new:
            return "length"
        if self.prior_tokens and (
            self.prior_tokens[-1] == self.eos_id
            or self.prior_tokens[-1] in self.stop_ids
        ):
            return "stop"
        return None

    def final_line(self) -> bytes:
        final: dict = {"done": True, "tokens": self.prior_tokens}
        if self.want_logprobs:
            final["logprobs"] = self.prior_lps
        return json.dumps(final).encode() + b"\n"


class Router:
    """Owns the backend table, the health/discovery loops, and the HTTP
    listener.  ``start()`` returns self; ``port`` is the bound port
    (0 → ephemeral, the ``NonBlockingGRPCServer.addr()`` pattern)."""

    def __init__(
        self,
        backends: tuple[str, ...] = (),
        registry_address: str = "",
        tls=None,
        host: str = "127.0.0.1",
        port: int = 0,
        health_interval: float = 2.0,
        discover_interval: float = 5.0,
        unhealthy_after: int = 2,
        request_timeout: float = 600.0,
        ssl_context=None,
        client_ssl_context=None,
        affinity_prefix_tokens: int = 32,
        affinity_slack: int = 2,
        disagg_prompt_tokens: int = 0,
        disagg_first_tokens: int = 1,
        disagg_ship_timeout: float = 30.0,
        migrate_timeout: float = 30.0,
        residency_aware: bool = True,
        prefix_fetch: bool = True,
        prefix_fetch_timeout: float = 10.0,
        prefix_fetch_min_tokens: int = 0,
        qos=None,
    ):
        """``ssl_context`` wraps the router's own listener in mTLS;
        ``client_ssl_context`` authenticates the router to mTLS
        backends (httptls module — the reference's mTLS-everywhere
        stance on the serving data plane)."""
        if not backends and not registry_address:
            raise ValueError(
                "router needs static --backend urls or a registry address"
            )
        self._lock = locksan.new_lock("Router._lock")
        self._backends: dict[str, Backend] = {
            url.rstrip("/"): Backend(id=url.rstrip("/"), url=url.rstrip("/"))
            for url in backends
        }
        self.registry_address = registry_address
        self._tls = tls
        self.health_interval = health_interval
        self.discover_interval = discover_interval
        self.unhealthy_after = unhealthy_after
        self.request_timeout = request_timeout
        self.affinity_prefix_tokens = affinity_prefix_tokens
        self.affinity_slack = affinity_slack
        # Disaggregated prefill/decode (serve/disagg.py): spliceable
        # generate streams whose prompt reaches disagg_prompt_tokens
        # run prefill on a prefill-pool backend (max_new_tokens clamped
        # to disagg_first_tokens, KV held), ship the KV blocks to a
        # decode-pool backend, and continue the stream there.  0 = off.
        if disagg_first_tokens < 1:
            raise ValueError(
                f"disagg_first_tokens must be >= 1, got "
                f"{disagg_first_tokens}"
            )
        self.disagg_prompt_tokens = disagg_prompt_tokens
        self.disagg_first_tokens = disagg_first_tokens
        self.disagg_ship_timeout = disagg_ship_timeout
        # Ship-outcome counters for /v1/stats (the shared Prometheus
        # instruments ride beside them; these are the router's own
        # lifetime view, lock-protected like the backend table).
        self._disagg = {
            "shipped": 0, "fell_back": 0, "prefill_only": 0,
            "ship_bytes": 0, "ship_seconds": 0.0,
        }
        # Live slot migration (ISSUE 17): wall-clock budget for one
        # slot ship (GET /v1/slot → PUT /v1/slot), and the router's
        # lifetime outcome view for /v1/stats.  The invariant the soak
        # pins: migrated + fell_back + gave_up == attempts — every
        # migrate marker a backend emits resolves to exactly one
        # outcome, or work is being thrown away silently.
        self.migrate_timeout = migrate_timeout
        self._migrations = {
            "attempts": 0, "migrated": 0, "fell_back": 0, "gave_up": 0,
            "ship_bytes": 0, "ship_seconds": 0.0,
        }
        # Fleet prefix residency (ISSUE 14): with residency_aware on,
        # generate traffic with a token-list prompt routes to the
        # backend whose resident-digest set (from the per-tick load
        # refetch) covers the longest prompt prefix — the load-slack
        # guard kept, so a hot prefix still cannot starve the fleet.
        # With prefix_fetch on, a miss where a sibling holds the
        # best-covering digest first ships that entry sibling→target
        # (GET /v1/kv?prefix= → PUT /v1/kv); the recompute prefill is
        # the unconditional fallback — a failed ship can slow a
        # request, never fail it.  residency_aware False is the
        # bench's residency-blind A/B control.
        self.residency_aware = residency_aware
        self.prefix_fetch = prefix_fetch
        self.prefix_fetch_timeout = prefix_fetch_timeout
        self.prefix_fetch_min_tokens = prefix_fetch_min_tokens
        self._prefix_counts = {
            "fetched": 0, "fell_back": 0, "ineligible": 0,
            "routed_resident": 0, "demoted": 0, "demote_failed": 0,
        }
        # Multi-tenant QoS (ISSUE 16): with a QosPolicy loaded, the
        # router is the quota layer — per-tenant token buckets
        # (request rate + generated-token budget) shed over-quota
        # traffic with 429 + a per-tenant Retry-After (shed reason
        # "quota", composing with the PR 6 taxonomy) BEFORE it ever
        # holds an engine slot.  None = quotas off; tenant resolution
        # and the x-oim-tenant forward still run, so backends can
        # fair-share even when the router doesn't throttle.
        self.qos = qos
        self._qos_tenants: dict[str, dict] = {}
        # (digest, target id) → monotonic instant of the last failed
        # ship: a persistently failing pair must not re-pay the fetch
        # timeout on every request (cooldown, not a blacklist).
        self._prefix_fetch_failed: dict[tuple, float] = {}
        # Ships currently in flight: a concurrent cohort burst must
        # not race N duplicate fetches of the same entry onto the same
        # target (the install is idempotent, but the duplicate wire
        # transfers and fetched-counter inflation are not free) — the
        # racers forward immediately and recompute, one ship lands.
        self._prefix_fetch_inflight: set = set()
        self._stop = threading.Event()
        self._rr = 0
        self._probing: set[str] = set()
        self._probe_pool = futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="router-probe"
        )
        self._watch_call = None  # in-flight WatchValues stream, for stop()
        from oim_tpu.serve.httptls import opener as _tls_opener

        self._client_ssl = client_ssl_context
        self._opener = _tls_opener(client_ssl_context)
        self._requests = metrics.registry().counter(
            "oim_route_requests_total",
            "Requests proxied by the serving router",
            labels=("backend", "outcome"),
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(
                self, code: int, payload: dict,
                headers: dict | None = None,
            ) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # Serving-plane CN pinning (httptls module docstring):
                # under mTLS the peer must carry a serve./route./user.
                # identity, not merely any deployment-CA cert.
                if not check_serving_peer(self):
                    return
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    metrics.write_exposition(self)
                elif path == "/v1/info":
                    # Backends are homogeneous replicas of one model;
                    # answer from any healthy one so clients behind the
                    # router can introspect without backend addresses.
                    # Full _proxy semantics apply: single retry,
                    # error attribution, metrics, trace propagation.
                    outer._proxy(self, "/v1/info", None, self._fwd_headers())
                elif path == "/healthz":
                    n = len(outer.healthy_backends())
                    self._json(
                        200 if n else 503,
                        {"ok": bool(n), "healthy_backends": n},
                        None if n else outer._retry_after_headers(),
                    )
                elif path == "/v1/stats":
                    self._json(200, outer.stats())
                elif path == "/v1/requests":
                    # Fleet-merged completed-request ring: every
                    # healthy backend's /debugz/requests in one reply
                    # (the /v1/stats load-aggregation pattern) — the
                    # `oimctl requests` data source.
                    self._json(200, outer.fleet_requests())
                elif path == "/debugz":
                    # Flight-recorder parity with every other daemon
                    # (PR 3): the router's own live event rings.
                    from oim_tpu.common import events as events_mod

                    self._json(200, events_mod.snapshot())
                elif path == "/debugz/profile":
                    # On-demand device profiling (ISSUE 18): status /
                    # tarball passthrough to ONE named backend.
                    outer._profile_proxy(self, None)
                else:
                    self._json(404, {"error": f"no such path {path}"})

            def _fwd_headers(self, extra: dict | None = None) -> dict:
                """Outbound headers for the backend hop: propagate the
                caller's trace context, like every other component
                boundary here, and the per-request deadline budget —
                the fleet entry point must not silently strip the
                header-based deadline knob."""
                headers = dict(extra or {})
                for name in ("traceparent", "x-oim-deadline-ms"):
                    if self.headers.get(name):
                        headers[name] = self.headers[name]
                return headers

            def do_POST(self):
                if not check_serving_peer(self):
                    return
                if self.path.split("?", 1)[0] == "/debugz/profile":
                    # On-demand device profiling (ISSUE 18): start a
                    # capture on ONE named backend (?backend=<id>).
                    # Not a PROXIED generation path — no tenant QoS
                    # charge, no pick-a-backend retry semantics.
                    length = int(self.headers.get("Content-Length", "0"))
                    outer._profile_proxy(self, self.rfile.read(length))
                    return
                if self.path not in PROXIED:
                    self._json(404, {"error": f"no such path {self.path}"})
                    return
                length = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(length)
                # Tenant QoS (ISSUE 16): resolve the tenant, charge its
                # token buckets, and shed over-quota traffic here —
                # before the request costs a backend connection, a
                # queue position, or an engine slot.
                tenant = outer._resolve_tenant(self)
                wait_s = outer._qos_throttle(
                    tenant, outer._request_tokens(self.path, body)
                )
                if wait_s is not None:
                    tier = (outer.qos or _QOS_DEFAULT).lookup(tenant).tier
                    metrics.SERVE_SHED.inc("quota")
                    metrics.SERVE_QOS.inc(tier, "throttled")
                    events.emit(
                        "qos.throttle",
                        component="oim-route",
                        severity=events.INFO,
                        subject=tenant,
                        tier=tier,
                        path=self.path,
                        retry_after_s=round(wait_s, 3),
                    )
                    self._json(
                        429,
                        {
                            "error": "tenant quota exhausted",
                            "tenant": tenant,
                            "tier": tier,
                            "retry_after_s": round(wait_s, 3),
                        },
                        # Per-TENANT Retry-After: when THIS bucket
                        # refills enough for one request, not the
                        # fleet-health hint the 503 path uses.
                        {"Retry-After": str(max(1, int(wait_s + 0.999)))},
                    )
                    return
                # Forward the RESOLVED tenant, never the raw client
                # header: an mTLS backend re-derives the tenant from
                # the router's cert chain anyway, while a plain-HTTP
                # backend (trusted perimeter behind this router)
                # honors the forwarded identity instead of collapsing
                # everything into "anon".
                headers = self._fwd_headers(
                    {
                        "Content-Type": "application/json",
                        "x-oim-tenant": tenant,
                    }
                )
                outer._proxy(self, self.path, body, headers)

        if ssl_context is not None:
            from oim_tpu.serve.httptls import TLSThreadingHTTPServer

            self._httpd = TLSThreadingHTTPServer(
                (host, port), Handler, ssl_context
            )
        else:
            self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.tls = ssl_context is not None
        self.host, self.port = self._httpd.server_address[:2]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True
        )
        self._discover_thread = (
            threading.Thread(target=self._discover_loop, daemon=True)
            if registry_address
            else None
        )

    # -- backend table -----------------------------------------------------

    def healthy_backends(self) -> list[Backend]:
        with self._lock:
            return [b for b in self._backends.values() if b.healthy]

    def _pick(
        self,
        exclude: set[str] = frozenset(),
        affinity_key: str | None = None,
        pool: str | None = None,
        residency: dict | None = None,
    ) -> Backend | None:
        """Least-active healthy backend, round-robin among ties.

        ``residency`` (the ``_residency_info`` result) upgrades
        prompt-prefix affinity from pure rendezvous to
        residency-AWARE: among backends whose advertised digest set
        covers the longest prefix of this prompt, take the least
        active — the prefill those digests represent is already
        resident there, so routing anywhere else recomputes it.  The
        same load-slack guard as rendezvous affinity applies (more
        than ``affinity_slack`` in-flight above the fleet's least
        active = overloaded, balance freely): a hot system prompt
        still cannot starve the fleet.  Rendezvous stays the
        tie-breaker and the fallback for traffic the router cannot
        hash (text surfaces) or prefixes nobody holds yet — it is
        what CREATES residency by steering a new prefix's cohort onto
        one backend.

        ``affinity_key`` biases the choice: the key's rendezvous-hash
        winner (stable under backend churn, no shared state) is taken
        as long as it isn't overloaded — more than ``affinity_slack``
        in-flight requests above the least-active backend.

        ``pool`` partitions a disaggregated fleet: "prefill"/"decode"
        picks strictly within that pool (the ship path's legs); None —
        regular traffic — avoids prefill-pool backends whenever any
        non-prefill backend is healthy, so decode chunks never queue
        behind long-prompt admissions (and degrades to the whole fleet
        rather than 503 when only prefill backends survive)."""
        with self._lock:
            ready = [
                b
                for b in self._backends.values()
                if b.healthy and b.id not in exclude
                # A draining backend (load flag, ISSUE 17) takes no NEW
                # work — it is migrating its slots out — while /v1/kv
                # and /v1/slot pulls (opener-direct, not _pick-routed)
                # keep flowing from it until teardown.
                and not (b.load or {}).get("draining")
            ]
            if pool is not None:
                ready = [b for b in ready if b.pool == pool]
            else:
                ready = [
                    b for b in ready if b.pool != "prefill"
                ] or ready
            if not ready:
                return None
            least = min(b.active for b in ready)
            if residency is not None:
                coverage = residency.get("coverage") or {}
                covered = [b for b in ready if coverage.get(b.id, 0) > 0]
                if covered:
                    top = max(coverage[b.id] for b in covered)
                    resident = min(
                        (b for b in covered if coverage[b.id] == top),
                        key=lambda b: b.active,
                    )
                    if resident.active <= least + self.affinity_slack:
                        resident.active += 1
                        self._prefix_counts["routed_resident"] += 1
                        return resident
            cacheable = [b for b in ready if b.prefix_cache]
            if affinity_key is not None and cacheable:
                affine = max(
                    cacheable,
                    key=lambda b: hashlib.sha256(
                        f"{affinity_key}|{b.id}".encode()
                    ).digest(),
                )
                if affine.active <= least + self.affinity_slack:
                    affine.active += 1
                    return affine
            tied = [b for b in ready if b.active == least]
            self._rr += 1
            chosen = tied[self._rr % len(tied)]
            chosen.active += 1
            return chosen

    def _release(self, backend: Backend, ok: bool) -> None:
        with self._lock:
            backend.active = max(0, backend.active - 1)
            if ok:
                backend.completed += 1
                backend.fails = 0
            # NOTE: HTTP-level errors (4xx/5xx) are NOT connection
            # failures — only _connection_failed flips health.

    def _connection_failed(self, backend: Backend) -> None:
        """A connect-level failure counts against health immediately —
        a dead backend must stop receiving traffic before the next
        probe tick."""
        with self._lock:
            backend.fails += 1
            if backend.fails >= self.unhealthy_after:
                if backend.healthy:
                    log.current().warning(
                        "backend unhealthy", backend=backend.id
                    )
                backend.healthy = False

    # -- proxying ----------------------------------------------------------

    def _affinity_key(self, path: str, body: bytes | None) -> str | None:
        """Prompt-prefix affinity for the generation endpoints
        (/v1/generate and the OpenAI-compatible /v1/completions):
        requests whose first ``affinity_prefix_tokens`` token ids match
        should share a backend (that backend's prefix cache holds their
        prefix).  Any parse problem means no affinity — never an
        error."""
        if (
            self.affinity_prefix_tokens <= 0
            or path not in (
                "/v1/generate", "/v1/completions", "/v1/chat/completions"
            )
            or not body
        ):
            return None
        try:
            payload = json.loads(body)
            ids = payload.get("tokens")
            text = payload.get("text")
            if path == "/v1/completions":
                # OpenAI field: prompt is a string or a token list.
                prompt = payload.get("prompt")
                if isinstance(prompt, list):
                    ids = prompt
                elif isinstance(prompt, str):
                    text = prompt
            elif path == "/v1/chat/completions":
                # Chat requests sharing a system prompt share their
                # leading messages; the serialized role:content stream
                # proxies the templated token prefix (the router has no
                # tokenizer or template).
                messages = payload.get("messages")
                if isinstance(messages, list):
                    text = "".join(
                        f"{m.get('role', '')}:{m.get('content', '')};"
                        for m in messages
                        if isinstance(m, dict)
                    )
            if ids is not None:
                prefix = ids[: self.affinity_prefix_tokens]
                if len(prefix) < self.affinity_prefix_tokens:
                    return None  # short prompts: balance freely
                return ",".join(str(int(t)) for t in prefix)
            # Text surface: the router has no tokenizer, so the leading
            # CHARACTERS proxy the token prefix (~4 chars/token).  Same
            # shared-prefix requests → same key → same backend cache.
            if isinstance(text, str):
                n_chars = 4 * self.affinity_prefix_tokens
                if len(text) < n_chars:
                    return None
                return "txt:" + text[:n_chars]
            return None
        except Exception:
            return None

    @staticmethod
    def _prompt_tokens(path: str, body: bytes | None) -> list[int] | None:
        """The request's token-id prompt, when it has one the router
        can hash (residency is digest-addressed, and digests hash
        token ids — the text/chat surfaces stay on rendezvous
        affinity).  Any parse problem means no tokens — never an
        error."""
        if body is None or path not in ("/v1/generate", "/v1/completions"):
            return None
        try:
            payload = json.loads(body)
            ids = payload.get("tokens")
            if path == "/v1/completions" and ids is None:
                prompt = payload.get("prompt")
                if isinstance(prompt, list):
                    ids = prompt
            if not isinstance(ids, list) or not ids:
                return None
            return [int(t) for t in ids]
        except Exception:
            return None

    def _residency_info(self, tokens: list[int] | None) -> dict | None:
        """Match the request's prompt against the fleet residency map
        (every healthy backend's advertised digest summary, refreshed
        each probe tick).  Returns None when residency routing is off
        or nothing matches; else::

            {"coverage": {backend id: covered tokens},
             "digest": best-covering digest, "tokens": its length,
             "holders": {ids holding it},
             "fetchable": {holder ids whose entry is paged (blocks>0)}}

        The router recomputes the digest over the prompt's leading n
        tokens for each distinct advertised length — a per-request
        memo keeps that to one hash per length, and the engine-side
        summary cap bounds the lengths."""
        if not self.residency_aware or not tokens or len(tokens) < 2:
            return None
        memo: dict[int, str] = {}

        def dig(n: int) -> str:
            if n not in memo:
                memo[n] = prefix_digest(tokens[:n])
            return memo[n]

        max_n = len(tokens) - 1  # the engine needs >= 1 tail token
        coverage: dict[str, int] = {}
        best_digest, best_n = None, 0
        holders: set[str] = set()
        fetchable: set[str] = set()
        with self._lock:
            summaries = [
                (b.id, list(b.load.get("prefix_digests") or ()))
                for b in self._backends.values()
                if b.healthy
            ]
        for bid, digests in summaries:
            cov = 0
            for entry in digests:
                if not isinstance(entry, dict):
                    continue
                try:
                    n = int(entry.get("tokens", 0))
                    blocks = int(entry.get("blocks", 0))
                except (TypeError, ValueError):
                    continue
                if n < 1 or n > max_n or entry.get("digest") != dig(n):
                    continue
                cov = max(cov, n)
                if n > best_n:
                    best_digest, best_n = entry["digest"], n
                    holders, fetchable = set(), set()
                if n == best_n:
                    holders.add(bid)
                    if blocks > 0:
                        fetchable.add(bid)
            if cov:
                coverage[bid] = cov
        if not coverage:
            return None
        return {
            "coverage": coverage,
            "digest": best_digest,
            "tokens": best_n,
            "holders": holders,
            "fetchable": fetchable,
        }

    def _retry_after_headers(self) -> dict:
        """Retry-After for router-level 503s: by the next health-probe
        tick a dead backend may be back (or a recovered one restored),
        so hint two probe intervals."""
        return {
            "Retry-After": str(max(1, int(self.health_interval * 2)))
        }

    # -- tenant QoS (ISSUE 16) ---------------------------------------------

    def _resolve_tenant(self, handler) -> str:
        """The requesting tenant's name: the mTLS peer CN when the
        router terminates TLS; else the ``x-oim-tenant`` header —
        honored ONLY on a plain-HTTP listener, where the deployment
        has already declared the perimeter trusted (doc/serving.md);
        else ``anon``.  Never raises: identity resolution failing open
        to the anonymous best-effort tier beats 500ing the data
        plane."""
        cn = peer_common_name(handler)
        if cn:
            return cn
        if not self.tls:
            claimed = (handler.headers.get("x-oim-tenant") or "").strip()
            if claimed:
                # Bounded: the name keys a capped state table and a
                # Prometheus label; a hostile megabyte header must not.
                return claimed[:128]
        return "anon"

    @staticmethod
    def _request_tokens(path: str, body: bytes | None) -> int:
        """Estimated token cost for quota charging: prompt tokens plus
        the decode budget (max_new_tokens — the engine's fair-share
        charge uses the same estimate, so router quota and engine
        share agree on what a request costs).  Token-id prompts count
        exactly; text prompts estimate ~4 chars/token.  Any parse
        problem charges 0 — malformed bodies are the backend
        validator's 4xx to issue, never a quota decision."""
        if body is None:
            return 0
        try:
            payload = json.loads(body)
            if not isinstance(payload, dict):
                return 0
            prompt = 0
            ids = payload.get("tokens")
            if ids is None and path == "/v1/completions":
                ids = payload.get("prompt")
            if isinstance(ids, list):
                prompt = len(ids)
            elif isinstance(payload.get("prompt"), str):
                prompt = len(payload["prompt"]) // 4
            elif isinstance(payload.get("text"), str):
                prompt = len(payload["text"]) // 4
            elif isinstance(payload.get("messages"), list):
                prompt = sum(
                    len(m.get("content", "")) // 4
                    for m in payload["messages"]
                    if isinstance(m, dict)
                    and isinstance(m.get("content"), str)
                )
            new = payload.get("max_new_tokens", payload.get("max_tokens"))
            new = int(new) if isinstance(new, int) and new > 0 else 0
            return max(0, prompt) + new
        except Exception:
            return 0

    def _qos_throttle(self, tenant: str, want_tokens: int) -> float | None:
        """Charge ``tenant``'s token buckets for one request costing
        ``want_tokens``; None = admitted, else the seconds until the
        drier bucket refills enough (the 429's Retry-After).  Two
        buckets per tenant, both classic leaky refill: request rate
        (rate_rps/effective_rate_burst) and generated-token budget
        (tokens_per_s/effective_token_burst).  A tenant whose policy
        sets neither is never throttled — quotas are opt-in per
        tenant, not a default tax."""
        policy = self.qos
        if policy is None:
            return None
        tp = policy.lookup(tenant)
        if tp.rate_rps <= 0.0 and tp.tokens_per_s <= 0.0:
            return None
        now = time.monotonic()
        with self._lock:
            row = self._qos_tenants.get(tenant)
            if row is None:
                if len(self._qos_tenants) >= _MAX_TENANT_ROWS:
                    idle = min(
                        self._qos_tenants,
                        key=lambda t: self._qos_tenants[t]["ts"],
                    )
                    del self._qos_tenants[idle]
                row = self._qos_tenants[tenant] = {
                    "rate_level": tp.effective_rate_burst,
                    "token_level": tp.effective_token_burst,
                    "t": now,
                    "admitted": 0,
                    "throttled": 0,
                    "tokens_charged": 0,
                    "ts": time.time(),
                }
            dt = max(0.0, now - row["t"])
            row["t"] = now
            row["ts"] = time.time()
            if tp.rate_rps > 0.0:
                row["rate_level"] = min(
                    tp.effective_rate_burst,
                    row["rate_level"] + dt * tp.rate_rps,
                )
            if tp.tokens_per_s > 0.0:
                row["token_level"] = min(
                    tp.effective_token_burst,
                    row["token_level"] + dt * tp.tokens_per_s,
                )
            waits = []
            if tp.rate_rps > 0.0 and row["rate_level"] < 1.0:
                waits.append((1.0 - row["rate_level"]) / tp.rate_rps)
            if (
                tp.tokens_per_s > 0.0
                and want_tokens > 0
                and row["token_level"] < float(want_tokens)
            ):
                waits.append(
                    (float(want_tokens) - row["token_level"])
                    / tp.tokens_per_s
                )
            if waits:
                row["throttled"] += 1
                return max(waits)
            if tp.rate_rps > 0.0:
                row["rate_level"] -= 1.0
            if tp.tokens_per_s > 0.0 and want_tokens > 0:
                row["token_level"] -= float(want_tokens)
                row["tokens_charged"] += want_tokens
            row["admitted"] += 1
            return None

    def _tenant_stats_locked(self) -> dict:
        """Fleet-merged per-tenant view for /v1/stats: the router's own
        quota rows joined with every healthy backend's engine-side
        tenant rows (queued/active/parked live counts, admission and
        preemption cumulatives, summed across the fleet) — the
        ``oimctl tenants`` data source.  Tolerant of backends
        predating the load-snapshot fields."""
        policy = self.qos or _QOS_DEFAULT
        rows: dict[str, dict] = {}

        def row(name: str) -> dict:
            if name not in rows:
                tp = policy.lookup(name)
                rows[name] = {
                    "tier": tp.tier,
                    "weight": tp.effective_weight,
                    "rate_rps": tp.rate_rps,
                    "tokens_per_s": tp.tokens_per_s,
                    "throttled": 0,
                    "tokens_charged": 0,
                    "queued": 0,
                    "active": 0,
                    "parked": 0,
                    "admitted": 0,
                    "preempted": 0,
                    "parked_victim": 0,
                    "requests": 0,
                    "tokens_out": 0,
                }
            return rows[name]

        for name, qrow in self._qos_tenants.items():
            merged = row(name)
            merged["throttled"] = qrow["throttled"]
            merged["tokens_charged"] = qrow["tokens_charged"]
        for b in self._backends.values():
            fleet = b.load.get("tenants")
            if not isinstance(fleet, dict):
                continue
            for name, erow in fleet.items():
                if not isinstance(erow, dict):
                    continue
                merged = row(str(name))
                if self.qos is None and isinstance(
                    erow.get("tier"), str
                ):
                    # No router policy: trust the engine's tier/weight
                    # labels rather than default-tiering everyone.
                    merged["tier"] = erow["tier"]
                    if isinstance(erow.get("weight"), (int, float)):
                        merged["weight"] = float(erow["weight"])
                for key in (
                    "queued", "active", "parked", "admitted",
                    "preempted", "parked_victim", "requests",
                    "tokens_out",
                ):
                    value = erow.get(key, 0)
                    if isinstance(value, int) and not isinstance(
                        value, bool
                    ):
                        merged[key] += value
        return rows

    def _proxy(
        self, handler, path: str, body: bytes | None, headers: dict
    ) -> None:
        """Open the router span for one proxied request, then run the
        failover loop under it (``_proxy_attempts``).

        Every attempt — the original AND each failover — carries the
        ROUTER span's context in its ``traceparent``, so the backends'
        server spans (and through them the engine phase spans) land
        under one trace id: a spliced failover renders as two server
        subtrees in a single ``oimctl trace`` tree, and a client that
        sent its own traceparent sees the router span join its trace."""
        parent = tracing.parse_traceparent(
            headers.get(tracing.TRACEPARENT_KEY, "") or ""
        )
        with tracing.start_span(
            f"route{path}", component="oim-route", parent=parent,
        ) as span:
            headers = dict(headers)
            headers[tracing.TRACEPARENT_KEY] = tracing.SpanContext(
                span.trace_id, span.span_id
            ).traceparent()
            self._proxy_attempts(handler, path, body, headers, span)

    def _proxy_attempts(
        self, handler, path: str, body: bytes | None, headers: dict,
        span,
    ) -> None:
        """Proxy one request to a healthy backend (``body`` None = GET —
        urllib's method selection; bytes = POST).

        Failure policy (module docstring "Retry & failover"): every
        backend that connection-fails OR dies mid-response stays in
        ``excluded`` for this request's lifetime — the loop can never
        hand the request back to a backend that just dropped it, and it
        terminates because each iteration either returns or excludes
        one more backend.  Work moves, it is not lost: buffered bodies
        resubmit whole, spliceable streams continue from the last
        emitted token on the next backend."""
        excluded: set[str] = set()
        failovers = 0  # backend deaths this request survived so far
        affinity_key = self._affinity_key(path, body)
        splice = _SpliceState.plan(path, body)
        # Fleet prefix residency (computed once per request — one
        # digest per advertised length, memoized): routes onto the
        # longest-covering backend and, on a miss a sibling could fix,
        # drives the pre-forward prefix ship below.
        residency = self._residency_info(self._prompt_tokens(path, body))
        # Track the relative x-oim-deadline-ms budget as an ABSOLUTE
        # instant here, and hand each attempt only what remains — a
        # failover must not restart the client's deadline from scratch
        # on the next backend.  (Body deadline_ms is the backend's to
        # enforce; through the router it is per-attempt — splice
        # continuations rewrite it, buffered resubmits do not.  Prefer
        # the header for routed traffic; doc/operations.md.)
        deadline_abs = None
        try:
            ms = float(headers.get("x-oim-deadline-ms", ""))
            if ms > 0:
                deadline_abs = time.monotonic() + ms / 1000.0
        except ValueError:
            pass
        if self._disagg_applicable(splice):
            # Disaggregated prefill/decode (serve/disagg.py): prefill
            # leg on a prefill-pool backend, KV blocks shipped to a
            # decode-pool backend, stream continued there.  "fallback"
            # lands in the ordinary loop below with the prefill leg's
            # tokens already in splice.prior_tokens — the splice
            # continuation (recompute prefill, token-identical greedy)
            # IS the fallback contract.
            outcome = self._disagg_attempt(
                handler, splice, headers, span, deadline_abs, excluded
            )
            if outcome != "fallback":
                return
        while True:
            if deadline_abs is not None:
                remaining_ms = (deadline_abs - time.monotonic()) * 1000.0
                if remaining_ms <= 0:
                    span.status = "error: deadline"
                    if failovers:
                        metrics.SERVE_FAILOVERS.inc("gave_up")
                    if splice is not None and splice.started:
                        self._write_client(
                            handler,
                            json.dumps({
                                "error": "deadline exceeded across "
                                "failover attempts"
                            }).encode() + b"\n",
                        )
                    else:
                        handler._json(504, {
                            "error": "deadline exceeded across "
                            "failover attempts"
                        })
                    return
                headers = dict(
                    headers,
                    **{"x-oim-deadline-ms": str(max(1, int(remaining_ms)))},
                )
            backend = self._pick(
                exclude=excluded, affinity_key=affinity_key,
                residency=residency,
            )
            if backend is None:
                span.status = "error: no healthy backend"
                if failovers:
                    metrics.SERVE_FAILOVERS.inc("gave_up")
                if splice is not None and splice.started:
                    # Bytes are already with the client: the protocol's
                    # terminal error line is all that is left to send.
                    self._write_client(
                        handler,
                        json.dumps({
                            "error": "no healthy serving backend to "
                            f"splice onto (tried {sorted(excluded)})"
                        }).encode() + b"\n",
                    )
                    return
                handler._json(
                    503,
                    {
                        "error": "no healthy serving backend"
                        + (
                            f" (tried {sorted(excluded)})" if excluded
                            else ""
                        )
                    },
                    self._retry_after_headers(),
                )
                return
            excluded.add(backend.id)
            # The last attempt wins the attr — with failovers, the
            # count says how many backends it took.
            span.attrs["backend"] = backend.id
            span.attrs["failovers"] = failovers
            if failovers == 0 and len(excluded) == 1:
                # First attempt only: a failover's priority is getting
                # the request served, not optimizing its prefill.
                self._maybe_fetch_prefix(backend, residency, deadline_abs)
                if deadline_abs is not None:
                    # The ship spent wall time AFTER the deadline
                    # header was stamped above: re-stamp with what
                    # actually remains, or the backend reads a budget
                    # the client no longer has.
                    remaining_ms = (
                        deadline_abs - time.monotonic()
                    ) * 1000.0
                    headers = dict(headers, **{
                        "x-oim-deadline-ms": str(
                            max(1, int(remaining_ms))
                        ),
                    })
            req_body = body if splice is None else splice.request_body()
            req = urllib.request.Request(
                backend.url + path, data=req_body, headers=headers
            )
            try:
                resp = self._opener.open(req, timeout=self.request_timeout)
            except urllib.error.HTTPError as exc:
                self._release(backend, ok=False)
                self._requests.inc(backend.id, f"http_{exc.code}")
                if splice is not None and splice.started:
                    # A continuation resubmit was refused (429/503/...):
                    # the client stream cannot carry a status line, so
                    # try the remaining backends for the splice.
                    log.current().warning(
                        "splice resubmit refused",
                        backend=backend.id, code=exc.code,
                    )
                    continue
                # The backend answered — pass its error through verbatim
                # (its body is JSON already, and its Retry-After backoff
                # hint must reach the client) and do not retry.
                payload = exc.read()
                handler.send_response(exc.code)
                handler.send_header("Content-Type", "application/json")
                handler.send_header("Content-Length", str(len(payload)))
                retry_after = exc.headers.get("Retry-After")
                if retry_after:
                    handler.send_header("Retry-After", retry_after)
                handler.end_headers()
                handler.wfile.write(payload)
                return
            except (urllib.error.URLError, OSError) as exc:
                # Connection-level failure before any response byte: the
                # backend is excluded above for the request's lifetime;
                # move on.
                self._release(backend, ok=False)
                self._connection_failed(backend)
                self._requests.inc(backend.id, "connect_error")
                log.current().warning(
                    "backend connect failed",
                    backend=backend.id,
                    error=str(getattr(exc, "reason", exc)),
                )
                continue
            if splice is not None:
                outcome = self._pipe_spliced(handler, backend, resp, splice)
                while outcome == "migrated":
                    # Live slot migration (ISSUE 17): the backend
                    # suspended this request for a migrate-out drain.
                    # Ship its slot to a sibling and splice the
                    # continuation there — already-decoded tokens
                    # resume from shipped KV, not a recompute.  The
                    # loop handles the target itself draining
                    # mid-continuation; "fallback" drops into the
                    # ordinary splice-recompute below, the
                    # unconditional contract: a failed migration can
                    # slow a request, never lose it.
                    outcome = self._migrate_attempt(
                        handler, splice, headers, span, deadline_abs,
                        excluded,
                    )
                if outcome == "fallback":
                    final = splice.finished()
                    if final is not None:
                        self._write_client(handler, splice.final_line())
                        return
                    continue  # recompute the remainder elsewhere
                if outcome == "died":
                    failovers += 1
                    final = splice.finished()
                    if final is not None:
                        # The emitted prefix already hit EOS/stop/budget:
                        # nothing left to decode — synthesize the final
                        # line instead of resubmitting zero tokens.
                        self._write_client(handler, splice.final_line())
                        metrics.SERVE_FAILOVERS.inc("spliced")
                        return
                    continue  # resubmit the remainder elsewhere
                if outcome == "done" and failovers:
                    metrics.SERVE_FAILOVERS.inc("spliced")
                return
            clen = resp.headers.get("Content-Length")
            if clen is None:
                # Close-delimited stream the router cannot splice
                # (text-prompt NDJSON, SSE completions): pass-through;
                # bytes already with the client on death means give up.
                self._pipe_stream(handler, backend, resp)
                return
            # Bounded JSON body: buffer it FULLY before forwarding, so a
            # backend death mid-body is invisible to the client — the
            # whole request simply resubmits on another backend.
            data = None
            with resp:
                try:
                    data = resp.read()
                except (OSError, http.client.HTTPException):
                    # IncompleteRead (a declared length cut short) is
                    # the killed-backend signature on buffered bodies.
                    data = None
            if data is None or len(data) < int(clen):
                self._release(backend, ok=False)
                self._connection_failed(backend)
                self._requests.inc(backend.id, "truncated")
                failovers += 1
                log.current().warning(
                    "backend died mid-response; resubmitting",
                    backend=backend.id, path=path,
                )
                continue
            if self._send_resp_headers(
                handler, resp, clen=clen
            ) and self._write_client(handler, data):
                self._release(backend, ok=True)
                self._requests.inc(backend.id, "ok")
            else:
                self._release(backend, ok=True)
                self._requests.inc(backend.id, "client_disconnected")
            if failovers:
                metrics.SERVE_FAILOVERS.inc("resubmitted")
            return

    # -- fleet prefix residency (serve/disagg.py, ISSUE 14) ----------------

    def _maybe_fetch_prefix(
        self, backend: Backend, residency, deadline_abs: float | None = None,
    ) -> None:
        """Turn a remote prefix hit into a block fetch instead of a
        prefill recompute: when the routed ``backend`` does NOT hold
        the request's best-covering digest but a sibling does, ship
        that entry sibling→target before forwarding (GET /v1/kv?prefix=
        → PUT /v1/kv).  Strictly best-effort — every failure counts,
        journals, and falls through to the recompute prefill the
        engine performs anyway (token-identical either way; a failed
        ship can slow a request, never fail it).  A (digest, target)
        pair that just failed cools down instead of re-paying the
        timeout per request.  A request whose remaining deadline
        budget could be eaten by the ship skips it outright: the
        fetch exists to save time, never to spend the client's."""
        if residency is None or not self.prefix_fetch:
            return
        if deadline_abs is not None and (
            deadline_abs - time.monotonic() <= self.prefix_fetch_timeout
        ):
            return
        digest, n = residency["digest"], residency["tokens"]
        if digest is None or n < max(1, self.prefix_fetch_min_tokens):
            return
        if residency["coverage"].get(backend.id, 0) >= n:
            return  # the target already holds the best cover: a hit
        holder_ids = residency["fetchable"] - {backend.id}
        # Target eligibility without a roundtrip: the ship installs
        # into a paged prefix cache, both advertised via /v1/info.
        with self._lock:
            target_ok = backend.prefix_cache and int(
                backend.load.get("kv_blocks_total") or 0
            ) > 0
            holders = [
                b for b in self._backends.values()
                if b.id in holder_ids and b.healthy
            ]
            holder = (
                min(holders, key=lambda b: b.active) if holders else None
            )
        if holder is None or not target_ok:
            with self._lock:
                self._prefix_counts["ineligible"] += 1
            metrics.SERVE_PREFIX_FETCH.inc("ineligible")
            return
        now = time.monotonic()
        with self._lock:
            t_failed = self._prefix_fetch_failed.get((digest, backend.id))
            if t_failed is not None and now - t_failed < 30.0:
                return  # cooling down; counted when it failed
            if (digest, backend.id) in self._prefix_fetch_inflight:
                return  # a sibling request's ship is already moving it
            self._prefix_fetch_inflight.add((digest, backend.id))
        t0 = time.monotonic()
        try:
            rows, nbytes = ship_prefix(
                self._opener.open, holder.url, digest, backend.url,
                timeout=self.prefix_fetch_timeout,
            )
        except Exception as exc:
            code = getattr(exc, "code", None)
            outcome = (
                "ineligible" if code in (404, 409) else "fell_back"
            )
            with self._lock:
                self._prefix_fetch_inflight.discard((digest, backend.id))
                self._prefix_counts[outcome] += 1
                self._prefix_fetch_failed[(digest, backend.id)] = now
                if len(self._prefix_fetch_failed) > 1024:
                    # Bounded: drop the stalest cooldown records.
                    for key in sorted(
                        self._prefix_fetch_failed,
                        key=self._prefix_fetch_failed.get,
                    )[:512]:
                        self._prefix_fetch_failed.pop(key, None)
            metrics.SERVE_PREFIX_FETCH.inc(outcome)
            events.emit(
                "prefix.fallback",
                component="oim-route",
                severity=events.WARNING,
                reason=f"{type(exc).__name__}: {exc}",
                digest=digest,
                src=holder.id,
                dst=backend.id,
            )
            log.current().warning(
                "prefix fetch fell back to recompute",
                digest=digest, src=holder.id, dst=backend.id,
                error=str(exc),
            )
            return
        dt = time.monotonic() - t0
        metrics.SERVE_PREFIX_FETCH.inc("fetched")
        metrics.SERVE_PREFIX_FETCH_SECONDS.observe(dt)
        with self._lock:
            self._prefix_fetch_inflight.discard((digest, backend.id))
            self._prefix_counts["fetched"] += 1
            self._prefix_fetch_failed.pop((digest, backend.id), None)
            # Optimistic map update so the cohort's next request reads
            # the target as covered NOW, not at the next probe tick
            # (the tick's refetch replaces this with the engine's own
            # summary; blocks>0 = fetchable onward).
            summary = list(backend.load.get("prefix_digests") or ())
            if not any(
                isinstance(e, dict) and e.get("digest") == digest
                for e in summary
            ):
                summary.append({
                    "digest": digest, "tokens": n,
                    "blocks": 1, "age_s": 0.0, "hits": 0,
                    "origin": "fetched",
                })
                backend.load = dict(
                    backend.load, prefix_digests=summary
                )
        events.emit(
            "prefix.fetch",
            component="oim-route",
            digest=digest,
            src=holder.id,
            dst=backend.id,
            rows=rows,
            bytes=nbytes,
            ms=round(dt * 1000.0, 1),
        )

    # -- disaggregated prefill/decode (serve/disagg.py) --------------------

    def _disagg_applicable(self, splice: "_SpliceState | None") -> bool:
        """Take the disaggregation path only for spliceable streams
        whose prompt reaches the threshold, whose budget extends past
        the first chunk, and while BOTH pools have a healthy member —
        a half-partitioned fleet serves everything mixed."""
        if splice is None or self.disagg_prompt_tokens <= 0:
            return False
        if len(splice.orig_tokens) < self.disagg_prompt_tokens:
            return False
        if splice.orig_max_new <= self.disagg_first_tokens:
            return False
        with self._lock:
            pools = {
                b.pool for b in self._backends.values() if b.healthy
            }
        return "prefill" in pools and "decode" in pools

    def _leg_headers(
        self, headers: dict, deadline_abs: float | None
    ) -> dict | None:
        """Per-leg outbound headers: the remaining deadline budget, the
        _proxy_attempts convention.  None = budget exhausted (the
        caller falls back; the ordinary loop answers the 504)."""
        if deadline_abs is None:
            return dict(headers)
        remaining_ms = (deadline_abs - time.monotonic()) * 1000.0
        if remaining_ms <= 0:
            return None
        return dict(
            headers,
            **{"x-oim-deadline-ms": str(max(1, int(remaining_ms)))},
        )

    def _disagg_fallback(
        self, reason: str, prefill: str = "", decode: str = ""
    ) -> None:
        """One ship gave up: count it, journal it, and let the caller
        drop into the splice-recompute continuation — the exactness-
        preserving fallback (PR 6 contract)."""
        with self._lock:
            self._disagg["fell_back"] += 1
        metrics.SERVE_DISAGG.inc("fell_back")
        events.emit(
            "disagg.fallback",
            component="oim-route",
            severity=events.WARNING,
            reason=reason,
            prefill=prefill,
            decode=decode,
        )
        log.current().warning(
            "KV ship fell back to splice recompute",
            reason=reason, prefill=prefill, decode=decode,
        )

    def _disagg_attempt(
        self, handler, splice: "_SpliceState", headers: dict, span,
        deadline_abs: float | None, excluded: set[str],
    ) -> str:
        """One disaggregated attempt: prefill leg → KV ship →
        continuation on the decode backend.  Returns "done" /
        "client_gone" (request over either way) or "fallback" (the
        ordinary splice loop finishes the request; any tokens the
        prefill leg emitted are already recorded).  Every failure
        releases what it reserved — held KV, staged imports, picked
        backends — so a ship that dies at any step leaks nothing."""
        backend = self._pick(pool="prefill")
        if backend is None:
            return "fallback"
        hdrs = self._leg_headers(headers, deadline_abs)
        if hdrs is None:
            # Counted like every other abandonment: the outcome
            # counters must sum to the disaggregation attempts, or the
            # fell_back-vs-shipped triage query reads healthy while
            # work is being thrown away.
            self._release(backend, ok=True)
            self._disagg_fallback("deadline exhausted before prefill leg")
            return "fallback"
        span.attrs["backend"] = backend.id
        req = urllib.request.Request(
            backend.url + "/v1/generate",
            data=splice.prefill_body(self.disagg_first_tokens),
            headers=hdrs,
        )
        try:
            resp = self._opener.open(req, timeout=self.request_timeout)
        except urllib.error.HTTPError as exc:
            # The prefill backend answered an error (shed, draining):
            # serve the whole request mixed instead of passing the
            # error through — disaggregation is an optimization, never
            # a new failure mode.
            self._release(backend, ok=False)
            self._requests.inc(backend.id, f"http_{exc.code}")
            self._disagg_fallback(
                f"prefill refused (HTTP {exc.code})", prefill=backend.id
            )
            return "fallback"
        except (urllib.error.URLError, OSError) as exc:
            self._release(backend, ok=False)
            self._connection_failed(backend)
            self._requests.inc(backend.id, "connect_error")
            excluded.add(backend.id)
            self._disagg_fallback(
                f"prefill connect failed "
                f"({getattr(exc, 'reason', exc)})",
                prefill=backend.id,
            )
            return "fallback"
        outcome = self._pipe_spliced(
            handler, backend, resp, splice, capture_done=True
        )
        if outcome in ("done", "client_gone"):
            # A terminal error line passed through, or our client left
            # — the request is over without a ship either way.
            return outcome
        if outcome == "migrated":
            self._abandon_migrate_marker(
                splice, excluded, "during disagg prefill leg"
            )
            return "fallback"
        if outcome == "died":
            excluded.add(backend.id)
            self._disagg_fallback(
                "prefill backend died mid-leg", prefill=backend.id
            )
            return "fallback"
        # outcome == "captured": the prefill leg completed its clamped
        # budget; its tokens are with the client AND in prior_tokens.
        done = splice.captured_done or {}
        rid = done.get("request_id")
        final = splice.finished()
        if final is not None:
            # EOS/stop landed inside the first chunk: nothing left to
            # decode — synthesize the terminal line; no ship happened.
            if rid is not None:
                release_kv(self._opener.open, backend.url, rid=rid)
            with self._lock:
                self._disagg["prefill_only"] += 1
            metrics.SERVE_DISAGG.inc("prefill_only")
            ok = self._write_client(handler, splice.final_line())
            return "done" if ok else "client_gone"
        if rid is None:
            self._disagg_fallback(
                "prefill leg carried no request_id", prefill=backend.id
            )
            return "fallback"
        decode_b = self._pick(pool="decode")
        if decode_b is None:
            release_kv(self._opener.open, backend.url, rid=rid)
            self._disagg_fallback(
                "no healthy decode backend", prefill=backend.id
            )
            return "fallback"
        t0 = time.monotonic()
        try:
            import_id, rows, nbytes = ship_kv(
                self._opener.open, backend.url, rid, decode_b.url,
                timeout=self.disagg_ship_timeout,
            )
        except Exception as exc:
            self._release(decode_b, ok=False)
            release_kv(self._opener.open, backend.url, rid=rid)
            self._disagg_fallback(
                f"ship failed ({type(exc).__name__}: {exc})",
                prefill=backend.id, decode=decode_b.id,
            )
            return "fallback"
        dt = time.monotonic() - t0
        # The decode side owns its copy now: release the prefill hold
        # at ship cadence instead of leaving it to the TTL sweep.
        release_kv(self._opener.open, backend.url, rid=rid)
        metrics.SERVE_KV_SHIP_SECONDS.observe(dt)
        metrics.SERVE_KV_SHIP_BYTES.inc(by=float(nbytes))
        with self._lock:
            self._disagg["ship_bytes"] += nbytes
            self._disagg["ship_seconds"] += dt
        events.emit(
            "disagg.ship",
            component="oim-route",
            prefill=backend.id,
            decode=decode_b.id,
            bytes=nbytes,
            rows=rows,
            ms=round(dt * 1000.0, 1),
        )
        hdrs = self._leg_headers(headers, deadline_abs)
        if hdrs is None:
            self._release(decode_b, ok=True)
            release_kv(
                self._opener.open, decode_b.url, import_id=import_id
            )
            self._disagg_fallback(
                "deadline exhausted after ship",
                prefill=backend.id, decode=decode_b.id,
            )
            return "fallback"  # the loop answers the 504
        span.attrs["backend"] = decode_b.id
        req = urllib.request.Request(
            decode_b.url + "/v1/generate",
            data=splice.request_body({"kv_import": import_id}),
            headers=hdrs,
        )
        try:
            resp = self._opener.open(req, timeout=self.request_timeout)
        except urllib.error.HTTPError as exc:
            self._release(decode_b, ok=False)
            self._requests.inc(decode_b.id, f"http_{exc.code}")
            release_kv(
                self._opener.open, decode_b.url, import_id=import_id
            )
            self._disagg_fallback(
                f"continuation refused (HTTP {exc.code})",
                prefill=backend.id, decode=decode_b.id,
            )
            return "fallback"
        except (urllib.error.URLError, OSError) as exc:
            self._release(decode_b, ok=False)
            self._connection_failed(decode_b)
            self._requests.inc(decode_b.id, "connect_error")
            excluded.add(decode_b.id)
            self._disagg_fallback(
                f"continuation connect failed "
                f"({getattr(exc, 'reason', exc)})",
                prefill=backend.id, decode=decode_b.id,
            )
            return "fallback"
        with self._lock:
            self._disagg["shipped"] += 1
        metrics.SERVE_DISAGG.inc("shipped")
        span.attrs["disagg"] = "shipped"
        outcome = self._pipe_spliced(handler, decode_b, resp, splice)
        if outcome == "died":
            # The ship itself succeeded; a decode-backend death
            # mid-continuation is the ordinary splice failover's to
            # finish (recompute on a surviving backend).
            excluded.add(decode_b.id)
            return "fallback"
        if outcome == "migrated":
            self._abandon_migrate_marker(
                splice, excluded, "during disagg continuation"
            )
            return "fallback"
        return outcome

    # -- live slot migration (serve/disagg.py, ISSUE 17) -------------------

    def _migrate_fallback(
        self, reason: str, src: str = "", target: str = ""
    ) -> None:
        """One migration gave up: count it, journal it, and let the
        caller drop into the splice-recompute continuation — the PR 6
        contract is the *unconditional* fallback (token-identical
        greedy), so a failed migration can slow a request, never fail
        it."""
        with self._lock:
            self._migrations["fell_back"] += 1
        metrics.SERVE_MIGRATIONS.inc("fell_back")
        events.emit(
            "migrate.fallback",
            component="oim-route",
            severity=events.WARNING,
            reason=reason,
            src=src,
            target=target,
        )
        log.current().warning(
            "slot migration fell back to splice recompute",
            reason=reason, src=src, target=target,
        )

    def _abandon_migrate_marker(
        self, splice: "_SpliceState", excluded: set, where: str
    ) -> None:
        """A migrate marker arrived on a leg that cannot take the
        slot-ship path (the disaggregation legs own their own
        fallback): release the suspended record, count the attempt as
        fell_back, and let splice recompute finish the request."""
        src, rid = splice.migrate_src, splice.migrate_rid
        splice.migrate_src = splice.migrate_rid = None
        with self._lock:
            self._migrations["attempts"] += 1
        if src is not None:
            excluded.add(src.id)
            if rid is not None:
                release_slot(self._opener.open, src.url, rid)
        self._migrate_fallback(
            f"migrate marker {where}", src=src.id if src else ""
        )

    def _migrate_attempt(
        self, handler, splice: "_SpliceState", headers: dict, span,
        deadline_abs: float | None, excluded: set[str],
    ) -> str:
        """One live-migration attempt after a migrate marker
        (``_splice_line``): ship the suspended slot off the draining
        ``splice.migrate_src`` to a sibling (GET /v1/slot → PUT
        /v1/slot) and splice the continuation there with
        ``kv_import`` — already-decoded tokens resume from shipped KV
        blocks, zero recompute.  Returns "done"/"client_gone" (request
        over), "migrated" (the TARGET began draining too — the caller
        loops back in), or "fallback" (the ordinary splice loop
        recomputes the remainder).  Every failure path releases what
        it reserved — the source's slot record, the target's staged
        import, picked backends — so a migration that dies at any
        step leaks nothing on either side."""
        src = splice.migrate_src
        rid = splice.migrate_rid
        splice.migrate_src = None
        splice.migrate_rid = None
        with self._lock:
            self._migrations["attempts"] += 1
        if src is not None:
            # Draining: no new work there.  The load flag catches it
            # at the next probe tick; this request must not wait for
            # one.
            excluded.add(src.id)
        if src is None or rid is None:
            self._migrate_fallback("migrate marker carried no rid/source")
            return "fallback"
        # QoS time pressure (ISSUE 16 composition): a best-effort
        # tenant with less remaining budget than one ship timeout
        # recomputes instead of paying the ship + continuation round
        # trips; premium/standard always try the ship (their slots
        # were also suspended FIRST, engine-side premium-first order).
        tenant = headers.get("x-oim-tenant") or "anon"
        tier = (self.qos or _QOS_DEFAULT).lookup(tenant).tier
        if (
            tier == "best_effort"
            and deadline_abs is not None
            and deadline_abs - time.monotonic() < self.migrate_timeout
        ):
            release_slot(self._opener.open, src.url, rid)
            self._migrate_fallback(
                "best-effort tenant under deadline pressure", src=src.id
            )
            return "fallback"
        target = self._pick(exclude=excluded)
        if target is None:
            # No sibling at all: nothing can take the shipped state —
            # and the recompute loop will find nothing either.  The
            # one outcome that is genuinely lost work.
            release_slot(self._opener.open, src.url, rid)
            with self._lock:
                self._migrations["gave_up"] += 1
            metrics.SERVE_MIGRATIONS.inc("gave_up")
            events.emit(
                "migrate.fallback",
                component="oim-route",
                severity=events.WARNING,
                reason="no sibling backend",
                src=src.id,
            )
            return "fallback"
        t0 = time.monotonic()
        try:
            import_id, rows, slot_meta, nbytes = ship_slot(
                self._opener.open, src.url, rid, target.url,
                timeout=self.migrate_timeout,
            )
        except Exception as exc:
            self._release(target, ok=False)
            release_slot(self._opener.open, src.url, rid)
            self._migrate_fallback(
                f"slot ship failed ({type(exc).__name__}: {exc})",
                src=src.id, target=target.id,
            )
            return "fallback"
        dt = time.monotonic() - t0
        # The target owns the copy now: release the source's record at
        # ship cadence instead of leaving it to the TTL sweep.
        release_slot(self._opener.open, src.url, rid)
        metrics.SERVE_KV_SHIP_SECONDS.observe(dt)
        metrics.SERVE_KV_SHIP_BYTES.inc(by=float(nbytes))
        with self._lock:
            self._migrations["ship_bytes"] += nbytes
            self._migrations["ship_seconds"] += dt
        hdrs = self._leg_headers(headers, deadline_abs)
        if hdrs is None:
            self._release(target, ok=True)
            release_kv(self._opener.open, target.url, import_id=import_id)
            self._migrate_fallback(
                "deadline exhausted after slot ship",
                src=src.id, target=target.id,
            )
            return "fallback"  # the loop answers the 504
        span.attrs["backend"] = target.id
        req = urllib.request.Request(
            target.url + "/v1/generate",
            data=splice.request_body({"kv_import": import_id}),
            headers=hdrs,
        )
        try:
            resp = self._opener.open(req, timeout=self.request_timeout)
        except urllib.error.HTTPError as exc:
            self._release(target, ok=False)
            self._requests.inc(target.id, f"http_{exc.code}")
            release_kv(self._opener.open, target.url, import_id=import_id)
            self._migrate_fallback(
                f"continuation refused (HTTP {exc.code})",
                src=src.id, target=target.id,
            )
            return "fallback"
        except (urllib.error.URLError, OSError) as exc:
            self._release(target, ok=False)
            self._connection_failed(target)
            self._requests.inc(target.id, "connect_error")
            excluded.add(target.id)
            self._migrate_fallback(
                f"continuation connect failed "
                f"({getattr(exc, 'reason', exc)})",
                src=src.id, target=target.id,
            )
            return "fallback"
        with self._lock:
            self._migrations["migrated"] += 1
        metrics.SERVE_MIGRATIONS.inc("migrated")
        span.attrs["migrated"] = True
        events.emit(
            "migrate.out",
            component="oim-route",
            src=src.id,
            target=target.id,
            rid=rid,
            rows=rows,
            bytes=nbytes,
            ms=round(dt * 1000.0, 1),
            tier=tier,
            sample_base=slot_meta.get("sample_base"),
        )
        outcome = self._pipe_spliced(handler, target, resp, splice)
        if outcome == "died":
            # The ship succeeded; a target death mid-continuation is
            # the ordinary splice failover's to finish (recompute on a
            # surviving backend).
            excluded.add(target.id)
            return "fallback"
        return outcome

    @staticmethod
    def _write_client(handler, data: bytes) -> bool:
        """Best-effort write to our client; False when it left."""
        try:
            handler.wfile.write(data)
            handler.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError):
            return False

    @staticmethod
    def _send_resp_headers(
        handler, resp, default_ctype: str = "application/json",
        clen: str | None = None,
    ) -> bool:
        """Forward one backend response's status + headers to our
        client (the one place every proxy path shares, so a new header
        to propagate is added once); False when the client left."""
        try:
            handler.send_response(resp.status)
            handler.send_header(
                "Content-Type",
                resp.headers.get("Content-Type", default_ctype),
            )
            if clen is not None:
                handler.send_header("Content-Length", clen)
            if resp.headers.get("traceparent"):
                handler.send_header(
                    "traceparent", resp.headers["traceparent"]
                )
            handler.end_headers()
            return True
        except (BrokenPipeError, ConnectionResetError):
            return False

    def _pipe_stream(self, handler, backend, resp) -> None:
        """Legacy pass-through for close-delimited streams the router
        cannot splice: chunk-by-chunk copy, socket errors attributed to
        the right side (resp.* = backend's, wfile.* = our client
        leaving)."""
        backend_died = client_gone = False
        ctype = resp.headers.get("Content-Type", "")
        with resp:
            if not self._send_resp_headers(handler, resp):
                client_gone = True
            while not (backend_died or client_gone):
                try:
                    chunk = resp.read(8192)
                except (OSError, http.client.HTTPException):
                    backend_died = True
                    break
                if not chunk:
                    break
                if not self._write_client(handler, chunk):
                    client_gone = True
        if backend_died:
            self._release(backend, ok=False)
            self._connection_failed(backend)
            self._requests.inc(backend.id, "truncated")
            # The docstring's promised terminal error line: this stream
            # cannot be spliced, but a detectable mid-read death must
            # not end it indistinguishable from completion.  (A killed
            # backend closing with a clean FIN is inherently
            # undetectable on a close-delimited stream — best effort.)
            # Framed per the stream's own protocol: SSE parsers discard
            # non-`data:` lines, so a bare JSON line would be invisible
            # to completions clients.
            payload = json.dumps({
                "error": "backend died mid-stream (unspliceable)"
            }).encode()
            self._write_client(
                handler,
                b"data: " + payload + b"\n\n"
                if "text/event-stream" in ctype else payload + b"\n",
            )
        elif client_gone:
            self._release(backend, ok=True)
            self._requests.inc(backend.id, "client_disconnected")
        else:
            self._release(backend, ok=True)
            self._requests.inc(backend.id, "ok")

    def _pipe_spliced(
        self, handler, backend, resp, splice: "_SpliceState",
        capture_done: bool = False,
    ) -> str:
        """Forward one backend's NDJSON generate stream line-by-line,
        recording emitted tokens so a mid-stream death can resume on
        another backend.  Returns "done" (terminal line delivered),
        "died" (EOF/socket error before a terminal line — the caller
        splices the remainder elsewhere; this attempt's tokens are
        folded into ``splice``), "client_gone", or — with
        ``capture_done`` (the disaggregation prefill leg) —
        "captured": the done line was SUPPRESSED from the client (the
        stream continues on a decode backend), its tokens folded into
        ``splice.prior_tokens`` and the object parked on
        ``splice.captured_done`` for the ship.

        Only COMPLETE lines are forwarded: a mid-line death discards
        the partial line (never forwarded, so client framing survives)
        and the continuation re-emits from the last complete token.
        The terminal done line is rewritten so its ``tokens`` (and
        ``logprobs``) span the WHOLE generation across every backend
        that served a part of it."""
        cur_tokens: list[int] = []
        cur_lps: list[float] = []
        buf = b""
        outcome = None
        with resp:
            if not splice.started:
                if self._send_resp_headers(
                    handler, resp, default_ctype="application/x-ndjson"
                ):
                    splice.started = True
                else:
                    outcome = "client_gone"
            while outcome is None:
                try:
                    chunk = resp.read(8192)
                except (OSError, http.client.HTTPException):
                    outcome = "died"
                    break
                if not chunk:
                    # Clean FIN without a terminal done/error line: the
                    # backend was killed mid-stream (close-delimited
                    # streams end-of-body and death look identical —
                    # the PROTOCOL's terminal line is the truncation
                    # proof).
                    outcome = "died"
                    break
                buf += chunk
                while b"\n" in buf and outcome is None:
                    line, buf = buf.split(b"\n", 1)
                    outcome = self._splice_line(
                        handler, splice, line, cur_tokens, cur_lps,
                        capture_done=capture_done,
                    )
        if outcome == "died":
            splice.prior_tokens += cur_tokens
            splice.prior_lps += cur_lps
            self._release(backend, ok=False)
            self._connection_failed(backend)
            self._requests.inc(backend.id, "truncated")
        elif outcome == "migrated":
            # The backend ANSWERED (alive, draining — not a death):
            # fold this attempt's tokens like a death so the
            # continuation resumes after them, but health and request
            # accounting read "served".
            splice.prior_tokens += cur_tokens
            splice.prior_lps += cur_lps
            splice.migrate_src = backend
            self._release(backend, ok=True)
            self._requests.inc(backend.id, "migrated")
        elif outcome == "client_gone":
            self._release(backend, ok=True)
            self._requests.inc(backend.id, "client_disconnected")
        else:
            self._release(backend, ok=True)
            self._requests.inc(backend.id, "ok")
        return outcome

    def _splice_line(
        self, handler, splice: "_SpliceState", line: bytes,
        cur_tokens: list, cur_lps: list, capture_done: bool = False,
    ) -> str | None:
        """Handle ONE complete NDJSON line: record tokens, rewrite the
        terminal done line to span all attempts, forward.  Returns the
        stream outcome when this line ends it, else None."""
        if not line.strip():
            return None
        try:
            obj = json.loads(line)
        except ValueError:
            obj = None
        if obj is None:
            return (
                None if self._write_client(handler, line + b"\n")
                else "client_gone"
            )
        if obj.get("migrate") and "error" in obj:
            # Live-migration marker (ISSUE 17): the backend suspended
            # this request for a drain.  NOT forwarded — the client
            # sees tokens, never the suspension; the router resumes
            # the stream on a sibling (or via splice recompute).  A
            # malformed marker falls through as the terminal error
            # line it otherwise is.
            try:
                splice.migrate_rid = int(obj["request_id"])
                return "migrated"
            except (KeyError, TypeError, ValueError):
                pass
        if obj.get("done"):
            if capture_done:
                # Disaggregation prefill leg: the stream is NOT over —
                # park the done object (tokens + the request_id that
                # addresses the held KV) and fold its tokens into the
                # prior record the continuation extends.
                splice.captured_done = obj
                splice.prior_tokens += [
                    int(t) for t in obj.get("tokens", ())
                ]
                splice.prior_lps += list(obj.get("logprobs") or ())
                return "captured"
            obj["tokens"] = splice.prior_tokens + [
                int(t) for t in obj.get("tokens", ())
            ]
            if "logprobs" in obj:
                obj["logprobs"] = splice.prior_lps + list(obj["logprobs"])
            ok = self._write_client(
                handler, json.dumps(obj).encode() + b"\n"
            )
            return "done" if ok else "client_gone"
        if "token" in obj:
            cur_tokens.append(int(obj["token"]))
            if "logprob" in obj:
                cur_lps.append(obj["logprob"])
        forwarded = self._write_client(handler, line + b"\n")
        if not forwarded:
            return "client_gone"
        # An {"error": ...} line is terminal per protocol: the backend
        # ANSWERED (it is alive; the request failed server-side), so it
        # passes through — failover is for backends that died.
        return "done" if "error" in obj else None

    # -- health + discovery ------------------------------------------------

    def _probe(self, backend: Backend) -> None:
        err: Exception | None = None
        try:
            with self._opener.open(
                backend.url + "/healthz", timeout=2
            ) as resp:
                ok = resp.status == 200
            if ok:
                # Every tick, not just the first: the capability fields
                # are fetch-once (static by contract) but the "load"
                # section is the backend's live pressure and must track
                # the probe cadence.
                self._fetch_info(backend)
        except Exception as exc:
            # Any probe failure means unhealthy — including non-OSError
            # ones like a malformed registry-advertised URL (ValueError);
            # swallowing those silently would pin the backend healthy
            # forever.  Logged below on the healthy→unhealthy transition
            # only, never per-tick.
            err = exc
            ok = False
        with self._lock:
            if ok:
                if not backend.healthy:
                    log.current().info(
                        "backend recovered", backend=backend.id
                    )
                backend.healthy = True
                backend.fails = 0
            else:
                backend.fails += 1
                if backend.fails >= self.unhealthy_after:
                    if backend.healthy:
                        log.current().warning(
                            "backend unhealthy",
                            backend=backend.id,
                            error=str(err) if err else "probe failed",
                        )
                    backend.healthy = False

    def _fetch_info(self, backend: Backend) -> None:
        """Per-probe /v1/info fetch: the capability fields (static by
        contract) land once, the live "load" section lands every time.
        Failure leaves the previous values, and info_fetched False, so
        the next probe retries."""
        try:
            with self._opener.open(
                backend.url + "/v1/info", timeout=2
            ) as resp:
                info = json.loads(resp.read())
        except Exception:
            return
        demote = False
        with self._lock:
            backend.prefix_cache = bool(
                info.get("engine", {}).get("prefix_cache_size", 0)
            )
            backend.pipeline_depth = int(
                info.get("engine", {}).get("pipeline_depth", 0)
            )
            backend.pool = str(info.get("pool") or "mixed")
            load = info.get("load")
            if isinstance(load, dict):
                backend.load = load
                # Drain flip (ISSUE 17): the first probe tick that sees
                # the draining flag runs the prefix demote-to-peer
                # sweep — once per draining episode, outside the lock
                # (it ships HTTP).
                if load.get("draining"):
                    if not backend.drain_demoted:
                        backend.drain_demoted = True
                        demote = self.prefix_fetch
                else:
                    backend.drain_demoted = False
            backend.info_fetched = True
            # Residency-map size gauge: distinct digests across the
            # fleet's advertised summaries, refreshed with the load
            # that feeds the map itself.
            metrics.ROUTE_RESIDENCY_DIGESTS.set(
                float(len(self._residency_digests_locked()))
            )
        if demote:
            self._demote_prefixes(backend)

    def _demote_prefixes(self, backend: Backend) -> None:
        """Prefix demote-to-peer on drain (ROADMAP item 5, ISSUE 17):
        when a backend's load flips to draining, ship its hottest
        exportable prefix entries (PR 14 ``export_kv_prefix`` /
        ``import_kv_prefix`` wire) to the least-loaded non-draining
        sibling before teardown destroys the fleet's cache working
        set.  Best-effort on the probe worker: a failed ship costs
        nothing but the attempt (the entry dies with the backend
        either way), counted per entry on the prefix-fetch counter so
        the cache-health triage sees fetches and demotions in one
        place."""
        entries = [
            e for e in (backend.load.get("prefix_digests") or ())
            if isinstance(e, dict) and e.get("digest")
            and int(e.get("blocks", 0) or 0) > 0
        ]
        if not entries:
            return
        # Hottest first: hits when the advertised summary carries
        # them; tokens (longest prefix = most prefill saved) as the
        # tie-breaker and the fallback sort key.
        entries.sort(
            key=lambda e: (
                int(e.get("hits", 0) or 0), int(e.get("tokens", 0) or 0)
            ),
            reverse=True,
        )
        with self._lock:
            ready = [
                b for b in self._backends.values()
                if b.healthy and b.id != backend.id and b.prefix_cache
                and not (b.load or {}).get("draining")
            ]
            target = min(ready, key=lambda b: b.active) if ready else None
        if target is None:
            return
        for entry in entries[:DRAIN_DEMOTE_ENTRIES]:
            digest = str(entry["digest"])
            try:
                rows, nbytes = ship_prefix(
                    self._opener.open, backend.url, digest, target.url,
                    timeout=self.prefix_fetch_timeout,
                )
            except Exception as exc:
                with self._lock:
                    self._prefix_counts["demote_failed"] += 1
                metrics.SERVE_PREFIX_FETCH.inc("demote_failed")
                events.emit(
                    "prefix.demote",
                    component="oim-route",
                    severity=events.WARNING,
                    src=backend.id,
                    target=target.id,
                    digest=digest[:16],
                    error=f"{type(exc).__name__}: {exc}",
                )
                continue
            with self._lock:
                self._prefix_counts["demoted"] += 1
            metrics.SERVE_PREFIX_FETCH.inc("demoted")
            events.emit(
                "prefix.demote",
                component="oim-route",
                src=backend.id,
                target=target.id,
                digest=digest[:16],
                rows=rows,
                bytes=nbytes,
            )

    def _residency_digests_locked(self) -> set:
        """Distinct advertised prefix digests fleet-wide (lock held) —
        the residency map's size, for the gauge and /v1/stats."""
        digests: set[str] = set()
        for b in self._backends.values():
            for entry in b.load.get("prefix_digests") or ():
                if isinstance(entry, dict) and entry.get("digest"):
                    digests.add(entry["digest"])
        return digests

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval):
            with self._lock:
                snapshot = [
                    b
                    for b in self._backends.values()
                    if b.id not in self._probing
                ]
                self._probing.update(b.id for b in snapshot)
            # Probe concurrently: N dead backends each eat their full
            # 2 s connect timeout, and a serial sweep would stall the
            # whole loop N× past health_interval, delaying both
            # unhealthy detection and recovery of live backends.  The
            # _probing guard means a stalled probe skips (not overlaps)
            # its backend on later ticks, so results never go stale.
            for backend in snapshot:
                try:
                    self._probe_pool.submit(self._probe_tracked, backend)
                except RuntimeError:  # pool shut down mid-sweep (stop())
                    with self._lock:
                        self._probing.discard(backend.id)
                    return

    def _probe_tracked(self, backend: Backend) -> None:
        try:
            self._probe(backend)
        finally:
            with self._lock:
                self._probing.discard(backend.id)

    def _discover_loop(self) -> None:
        """Event-driven discovery: hold a registry WatchValues stream on
        the ``serve/`` prefix and apply each mutation as it happens — a
        deregistered or lease-expired backend leaves the table at the
        DELETE event, in milliseconds, not at the next poll tick.  On
        stream failure, back off ``discover_interval`` and reconnect
        (the controller heartbeat's never-die rule); each reconnect
        starts with a full reconcile, so missed events can't strand a
        stale backend."""
        while not self._stop.is_set():
            try:
                self._watch_discover()
            except Exception as exc:
                if self._stop.is_set():
                    return
                log.current().warning(
                    "registry watch discovery failed; polling this tick",
                    registry=self.registry_address,
                    error=str(exc),
                )
                # Degrade to poll cadence while the watch path is broken
                # (old server, watcher cap RESOURCE_EXHAUSTED, registry
                # bounce): slower discovery beats none.
                try:
                    self._discover_once()
                except Exception:
                    pass
            if self._stop.wait(self.discover_interval):
                return

    def _watch_discover(self) -> None:
        """One watch session.  ``send_initial`` snapshot → reconcile at
        the ``initial_done`` marker → apply live events.  The server
        subscribes BEFORE snapshotting, so nothing falls between the
        snapshot and the event stream (doc/spec.md WatchValuesReply)."""
        from oim_tpu.common.regdial import registry_channel
        from oim_tpu.spec import REGISTRY, oim_pb2

        with registry_channel(self.registry_address, self._tls) as channel:
            stub = REGISTRY.stub(channel)
            call = stub.WatchValues(
                oim_pb2.WatchValuesRequest(path="serve", send_initial=True)
            )
            self._watch_call = call
            # stop() sets _stop BEFORE reading _watch_call; if it ran in
            # the window before the assignment above it found None and
            # cancelled nothing — re-check here so the discover thread
            # cannot block forever in the stream iteration on a quiet
            # registry.
            if self._stop.is_set():
                call.cancel()
                self._watch_call = None
                return
            try:
                snapshot: dict[str, str] = {}
                in_snapshot = True
                for event in call:
                    if self._stop.is_set():
                        return
                    if in_snapshot:
                        if event.initial_done:
                            self._reconcile(snapshot)
                            in_snapshot = False
                            continue
                        sid = self._serve_id(event.value.path)
                        if sid is not None and event.value.value:
                            snapshot[sid] = event.value.value.rstrip("/")
                        continue
                    self._apply_event(event.value.path, event.value.value)
            finally:
                self._watch_call = None
                call.cancel()

    @staticmethod
    def _serve_id(path: str) -> str | None:
        parts = path.split("/")
        if len(parts) == 3 and parts[0] == "serve" and parts[2] == "address":
            return parts[1]
        return None

    def _apply_event(self, path: str, value: str) -> None:
        sid = self._serve_id(path)
        if sid is None:
            return
        with self._lock:
            if value == "":
                b = self._backends.get(sid)
                if b is not None and b.from_registry:
                    log.current().info("backend withdrawn", backend=sid)
                    del self._backends[sid]
                return
            self._upsert_locked(sid, value.rstrip("/"))

    def _upsert_locked(self, sid: str, url: str) -> None:
        existing = self._backends.get(sid)
        if existing is None:
            log.current().info("backend discovered", backend=sid, url=url)
            self._backends[sid] = Backend(id=sid, url=url, from_registry=True)
        elif existing.url != url:
            # Same id, new address: the instance moved (the
            # channel-cache-era controller-move semantics).  A restart
            # may change capabilities too — re-fetch /v1/info.
            log.current().info("backend moved", backend=sid, url=url)
            existing.url = url
            existing.healthy = True
            existing.fails = 0
            existing.info_fetched = False
            existing.prefix_cache = False

    def _reconcile(self, found: dict[str, str]) -> None:
        """Full-state reconcile: registry-sourced entries come and go
        with their keys; static ones are permanent."""
        with self._lock:
            for sid, url in found.items():
                self._upsert_locked(sid, url)
            for sid in list(self._backends):
                b = self._backends[sid]
                if b.from_registry and sid not in found:
                    log.current().info("backend withdrawn", backend=sid)
                    del self._backends[sid]

    def _discover_once(self) -> None:
        """One-shot poll + reconcile (kept for embedders and tests; the
        running router uses the watch stream)."""
        from oim_tpu.common.regdial import registry_channel
        from oim_tpu.spec import REGISTRY, oim_pb2

        with registry_channel(self.registry_address, self._tls) as channel:
            reply = REGISTRY.stub(channel).GetValues(
                oim_pb2.GetValuesRequest(path="serve"), timeout=10
            )
        found: dict[str, str] = {}
        for value in reply.values:
            sid = self._serve_id(value.path)
            if sid:
                found[sid] = value.value.rstrip("/")
        self._reconcile(found)

    # -- stats / lifecycle ---------------------------------------------------

    def fleet_requests(self) -> dict:
        """Fleet-merged completed-request forensics (``GET
        /v1/requests``): every known backend's ``/debugz/requests``
        ring in one reply, each entry stamped with its backend id,
        sorted oldest→newest by completion wall time.  A backend that
        fails the fetch is reported in ``errors`` rather than silently
        missing — partial forensics must say they are partial."""
        def fetch(backend: Backend):
            try:
                with self._opener.open(
                    backend.url + "/debugz/requests", timeout=5
                ) as resp:
                    return backend.id, json.loads(resp.read()), None
            except Exception as exc:
                return backend.id, None, str(getattr(exc, "reason", exc))

        # ALL backends, not just healthy ones: a stalled backend (the
        # watchdog flipped /healthz, the router routed around it) is
        # exactly the one whose outcome=stalled ring entries the triage
        # needs, and its HTTP listener usually still answers /debugz —
        # a truly dead one lands in ``errors`` via its connect failure.
        with self._lock:
            backends = list(self._backends.values())
        # Concurrent fetches on a per-call pool sized to the fleet
        # (capped): a serial sweep would make /v1/requests O(fleet)
        # with one hung-but-listening backend adding its whole 5s
        # timeout — exactly during the incident the endpoint exists to
        # triage — and borrowing the shared 8-worker probe pool would
        # both re-serialize past 8 backends and starve health probes
        # of workers.  The ``with`` joins all fetches before returning.
        merged: list[dict] = []
        dropped = 0
        errors: dict[str, str] = {}
        with futures.ThreadPoolExecutor(
            max_workers=max(1, min(32, len(backends))),
            thread_name_prefix="router-forensics",
        ) as pool:
            pending = [(b.id, pool.submit(fetch, b)) for b in backends]
            for queued_id, future in pending:
                try:
                    bid, doc, err = future.result()
                except futures.CancelledError:  # pragma: no cover
                    errors[queued_id] = "fetch cancelled"
                    continue
                if doc is None:
                    errors[bid] = err
                    continue
                for entry in doc.get("requests", ()):
                    if isinstance(entry, dict):
                        merged.append(dict(entry, backend=bid))
                dropped += int(doc.get("dropped", 0) or 0)
        merged.sort(key=lambda e: float(e.get("ts", 0.0) or 0.0))
        return {"requests": merged, "dropped": dropped, "errors": errors}

    def _profile_proxy(self, handler, body: bytes | None) -> None:
        """Fan ``/debugz/profile`` out to ONE named backend
        (``?backend=<id>``, backend URL accepted too): the profiler is
        per-process device state, so a fleet-wide capture makes no
        sense — ``oimctl profile --router URL --backend ID`` names the
        replica to trace.  ``body`` None = GET passthrough (status /
        ``?download=1`` tarball), bytes = POST start."""
        parts = urllib.parse.urlsplit(handler.path)
        query = urllib.parse.parse_qs(parts.query)
        name = (query.get("backend") or [""])[0]
        if not name:
            handler._json(400, {
                "error": "missing ?backend=<id> — the profiler is "
                         "per-backend state; pick one replica",
            })
            return
        with self._lock:
            backend = self._backends.get(name)
            if backend is None:
                for b in self._backends.values():
                    if b.url == name:
                        backend = b
                        break
            known = sorted(self._backends)
        if backend is None:
            handler._json(404, {
                "error": f"no such backend {name!r}",
                "backends": known,
            })
            return
        passthrough = urllib.parse.urlencode(
            {k: v for k, v in query.items() if k != "backend"},
            doseq=True,
        )
        url = backend.url + "/debugz/profile" + (
            "?" + passthrough if passthrough else ""
        )
        req = urllib.request.Request(
            url,
            data=body,
            headers=(
                {"Content-Type": "application/json"}
                if body is not None else {}
            ),
            method="POST" if body is not None else "GET",
        )
        try:
            # Generous timeout: a capture window is ≤60s and the POST
            # returns immediately (202) — only the download of a large
            # tarball approaches it.
            with self._opener.open(req, timeout=75) as resp:
                payload = resp.read()
                code = resp.status
                ctype = resp.headers.get(
                    "Content-Type", "application/json"
                )
                cdisp = resp.headers.get("Content-Disposition", "")
        except urllib.error.HTTPError as exc:
            # Backend verdicts (409 capture-in-progress, 404 nothing to
            # download) pass through verbatim — the router adds routing,
            # not policy.
            payload = exc.read()
            code = exc.code
            ctype = (
                exc.headers.get("Content-Type", "application/json")
                if exc.headers else "application/json"
            )
            cdisp = ""
        except Exception as exc:
            handler._json(502, {
                "error": f"backend {backend.id} unreachable: "
                         f"{getattr(exc, 'reason', exc)}",
            })
            return
        handler.send_response(code)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(payload)))
        if cdisp:
            handler.send_header("Content-Disposition", cdisp)
        handler.end_headers()
        handler.wfile.write(payload)

    def stats(self) -> dict:
        with self._lock:
            return {
                "backends": {
                    b.id: {
                        "url": b.url,
                        "healthy": b.healthy,
                        "active": b.active,
                        "completed": b.completed,
                        "from_registry": b.from_registry,
                        # 0 until the first /v1/info fetch succeeds.
                        "pipeline_depth": b.pipeline_depth,
                        # Disaggregation pool role ("mixed" until the
                        # first /v1/info fetch).
                        "pool": b.pool,
                        # {} until the first probe-tick info fetch; then
                        # the backend's live load/<cn> snapshot.
                        "load": dict(b.load),
                    }
                    for b in self._backends.values()
                },
                # KV-ship outcomes (serve/disagg.py): shipped /
                # fell_back / prefill_only counts plus the shipped
                # bytes and wall seconds — the fleet's disaggregation
                # health at a glance (doc/operations.md incident
                # queries).
                "disagg": {
                    **{k: self._disagg[k] for k in (
                        "shipped", "fell_back", "prefill_only",
                        "ship_bytes",
                    )},
                    "ship_seconds": round(
                        self._disagg["ship_seconds"], 4
                    ),
                },
                # Fleet prefix residency (ISSUE 14): the residency
                # map's size, the router's ship outcomes, how many
                # requests routed onto a resident backend, and the
                # FLEET prefix-hit rate (per-backend engine counters
                # summed from the load snapshots) — `oimctl top`'s PFX
                # summary and the staleness incident queries read this.
                "prefix": {
                    "residency_digests": len(
                        self._residency_digests_locked()
                    ),
                    "residency_aware": self.residency_aware,
                    **dict(self._prefix_counts),
                    "fleet_hits": sum(
                        int(b.load.get("prefix_hits") or 0)
                        for b in self._backends.values()
                    ),
                    "fleet_misses": sum(
                        int(b.load.get("prefix_misses") or 0)
                        for b in self._backends.values()
                    ),
                },
                # Live slot migration (ISSUE 17): marker attempts and
                # their outcomes (migrated + fell_back + gave_up ==
                # attempts — the soak's invariant), plus shipped bytes
                # and wall seconds.  The drain runbook's triage query:
                # fell_back climbing = ships failing (capacity,
                # geometry, chaos); gave_up nonzero = drains with no
                # sibling — work IS being lost.
                "migrations": {
                    **{k: self._migrations[k] for k in (
                        "attempts", "migrated", "fell_back", "gave_up",
                        "ship_bytes",
                    )},
                    "ship_seconds": round(
                        self._migrations["ship_seconds"], 4
                    ),
                },
                # Multi-tenant QoS (ISSUE 16): whether the router
                # enforces quotas, the fleet-merged per-tenant rows
                # (`oimctl tenants`), and the fleet preemption total
                # (engine-side priority parks, summed from the load
                # snapshots).
                "qos": {
                    "enabled": self.qos is not None,
                    "tenants": self._tenant_stats_locked(),
                    "fleet_preemptions": sum(
                        int(b.load.get("qos_preemptions") or 0)
                        for b in self._backends.values()
                    ),
                },
                # Fleet KV-tier flow (ISSUE 18): the hierarchical-KV-
                # store totals summed from the per-backend load
                # snapshots (`oimctl kv`'s fleet line and the ROADMAP
                # item 5 autoscaling input).  .get() throughout:
                # old-schema publishers simply contribute zeros.
                "kv": {
                    key: sum(
                        int(b.load.get(key) or 0)
                        for b in self._backends.values()
                    )
                    for key in (
                        "kv_demotions", "kv_promotions",
                        "kv_demote_bytes", "kv_promote_bytes",
                        "kv_parks", "kv_unparks", "parked_slots",
                        "kv_blocks_total", "kv_blocks_free",
                        "kv_host_blocks_total", "kv_host_blocks_free",
                    )
                },
            }

    def start(self) -> "Router":
        self._http_thread.start()
        self._health_thread.start()
        if self._discover_thread is not None:
            self._discover_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        call = self._watch_call
        if call is not None:
            call.cancel()  # unblock the discover thread's stream iteration
        # shutdown() handshakes with serve_forever and deadlocks if the
        # listener thread never started (constructed-but-unstarted
        # routers are legal — unit tests, failed startups).
        if self._http_thread.is_alive():
            self._httpd.shutdown()
        self._httpd.server_close()
        # Join the loops before tearing down what they touch: an
        # unjoined health/discover thread can fire one more probe or
        # reconcile against the closed probe pool after stop() returns
        # (and a stopped-then-restarted test registry would see a ghost
        # watcher from the previous router).  Bounded: both loops
        # observe _stop within one wait() tick and the watch call is
        # already cancelled.
        for thread in (
            self._http_thread, self._health_thread, self._discover_thread
        ):
            if thread is not None and thread.is_alive():
                thread.join(timeout=5)
        self._probe_pool.shutdown(wait=False, cancel_futures=True)
        with self._lock:
            # Cancelled futures never reach _probe_tracked's finally.
            self._probing.clear()
