"""HTTP front end for the serving engine.

Dependency-free (stdlib ``http.server``, same stance as
``common/metrics.py``'s exposition server): POST /v1/generate with a JSON
body, blocking until the generation completes; the engine loop runs in a
background driver thread so concurrent requests batch onto slots.

API:
  POST /v1/generate   {"tokens": [int...], "max_new_tokens": N,
                       "temperature": 0.0, "seed": 0, "eos_id": null,
                       "stream": false, "logprobs": false,
                       "top_p": null, "min_p": 0.0,
                       "repetition_penalty": 1.0, "presence_penalty": 0.0,
                       "frequency_penalty": 0.0,
                       "cache_prefix": false, "stop_ids": []}
                    → {"tokens": [int...]}   (generated only, EOS included;
                    "logprobs": true adds each token's log-softmax under
                    the model's raw temperature-1 distribution)
                    With "stream": true the response is NDJSON, one
                    {"token": t} line per generated token as it decodes
                    (tokens arrive in chunk-sized bursts; with
                    "logprobs": true each line adds "logprob"),
                    terminated by {"done": true, "tokens": [...]} (plus
                    "logprobs": [...] when requested) or {"error": ...}.
  POST /v1/embed      {"tokens": [int...]} → {"embedding": [float...],
                    "dim": d} — mean-pooled, L2-normalized final hidden
                    state (the embeddings surface).
  POST /v1/beam       {"tokens": [int...], "max_new_tokens": N,
                    "beam_size": 4, "alpha": 0.6, "eos_id": null}
                    → {"tokens": [int...], "score": float} — latency-mode
                    beam search (EOS-aware, GNMT length-normalized) on
                    the engine's model; beam_size 1 equals greedy
                    /v1/generate output exactly.
  POST /v1/completions  OpenAI-compatible completions: {"prompt":
                    str|[int...], "max_tokens": N, "temperature", "top_p",
                    "n", "seed", "stop": str|[str...], "stream": false}
                    → {"object": "text_completion", "choices": [...],
                    "usage": {...}}.  String prompts/stops and SSE
                    streaming need --tokenizer-dir; token-list prompts
                    work anywhere (choices carry "tokens").  stream=true
                    answers Server-Sent Events chunks ending in
                    "data: [DONE]".
  POST /v1/chat/completions  OpenAI chat shape: {"messages": [{"role",
                    "content"}...], ...same params} rendered through the
                    tokenizer's OWN chat template (tokenizer_config.json
                    next to imported weights; refused with a clear error
                    when the tokenizer carries none) → choices carry
                    {"message": {"role": "assistant", "content": ...}};
                    stream=true sends chat.completion.chunk deltas.
  GET  /healthz      → {"ok": true} (503 after a driver-thread death OR
                    a watchdog-detected decode stall — the router
                    routes around either)
  GET  /v1/stats     → engine stats (slots, queue depth, tokens
                    generated, and the decode-pipeline forensics:
                    pipeline_depth, dispatch_seconds vs readback_seconds
                    — the dispatch-wait/fetch-wait split — plus
                    overlap_ratio and device_idle_seconds; see
                    doc/operations.md "Serving pipeline tuning")
  GET  /v1/info      → static model/engine description (geometry, params,
                    capacity shape, live features) — cacheable, EXCEPT
                    the "load" section, which mirrors the live
                    ``load/<cn>`` registry snapshot (queue depth,
                    busy/total slots, token rate, shed counters,
                    brownout) for the router and the autoscaler
  GET  /v1/weights   → streamed weight fetch for peer bring-up: an
                    8-byte big-endian manifest length, a JSON manifest
                    ([{"name", "dtype", "shape"}...]) and each leaf's
                    raw bytes in manifest order.  A scaling-out replica
                    restores from a serving sibling over this
                    (checkpoint.load_params_from_peer) instead of
                    re-reading blob storage — bring-up bounded by
                    network, not checkpoint cold-start
  GET  /v1/kv?rid=N  → streamed KV export for disaggregated
                    prefill/decode (serve/disagg.py): a completed
                    ``hold_kv`` request's paged-KV blocks as manifest
                    + raw leaves (the /v1/weights framing).  404 when
                    nothing is held for that rid, 409 on a dense
                    (non-paged) engine — the router falls back to
                    splice recompute on either.
  GET  /v1/kv?prefix=D → streamed export of the resident prefix-cache
                    entry whose content digest is D (fleet prefix
                    residency, doc/serving.md): the entry's
                    block-aligned KV in the same framing, installable
                    on a sibling without recomputing the prefill.
                    404 on an unknown digest, 409 on dense/kv4 —
                    the fetcher's recompute path is the fallback.
  PUT  /v1/kv        ← stage a shipped KV state: a request-hold
                    transfer becomes a continuation ``kv_import``; a
                    prefix transfer installs a refcounted
                    prefix-cache entry (idempotent when already
                    resident).  Both geometry-validated against this
                    engine (409 on mismatch), block reservation
                    all-or-nothing (429 + Retry-After on pool
                    exhaustion — capacity backpressure).
  DELETE /v1/kv?rid=N|import=N → release a KV hold / staged import
                    (the router's post-ship cleanup; the TTL sweep is
                    the backstop when the orchestrator died mid-ship)
  GET  /metrics      → Prometheus exposition (shared registry)
  GET  /debugz      → live flight-recorder event rings (common/events.py)
  GET  /debugz/requests → the recently-completed-request ring: one
                    record per finalized request (rid, tenant CN, trace
                    id, per-phase durations queue/admit/prefill/decode/
                    stream, token counts, outcome) plus the drop-oldest
                    eviction count — the slow-request forensics surface
                    (doc/operations.md "Request forensics"); the router
                    merges these fleet-wide at /v1/requests
  POST /debugz/profile {"seconds": S} → start a bounded on-demand
                    ``jax.profiler`` trace into the flight dir
                    (one-at-a-time guarded: 409 while one runs; served
                    BEFORE the error latch — forensics must work on a
                    wedged backend)
  GET  /debugz/profile → profiler status JSON; ``?download=1`` streams
                    the finished trace directory as a .tar.gz
                    (``oimctl profile`` drives the full cycle; the
                    router fans out to a named backend)

Fault tolerance (doc/operations.md "Serving failure modes"): every
generation endpoint takes a relative deadline budget — ``deadline_ms``
in the body or the ``x-oim-deadline-ms`` header — enforced in the
admission queue (expired entries shed with 429 + Retry-After before
touching a slot) and mid-decode (504, slot freed at the next pipeline
boundary).  All 429/503 sheds carry a ``Retry-After`` header computed
from the engine's observed marginal token rate.  A client that
disconnects mid-stream cancels its request (the slot stops burning).
With ``watchdog_interval`` > 0 a ``StallWatchdog`` detects a wedged
device (a decode chunk exceeding a multiple of its EWMA wall), fails
in-flight requests fast (503, retryable elsewhere), and flips
/healthz.

The engine is tokenizer-agnostic by design — clients speak token ids, the
same boundary the CSI driver keeps by speaking device paths rather than
framework objects.  With ``--tokenizer-dir`` (serve/texttok.py) the HTTP
layer — not the engine — additionally accepts ``{"text": ...}`` in place
of ``tokens`` on generate/beam/embed, defaults text requests' EOS to the
tokenizer's, and adds decoded ``text`` to replies (streaming lines carry
incremental ``text`` deltas whose concatenation equals the final
decode).
"""

from __future__ import annotations

import json
import os
import queue
import tarfile
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from oim_tpu.common import events, metrics, tracing
from oim_tpu.common import locksan
from oim_tpu.serve import disagg
from oim_tpu.serve.httptls import check_serving_peer, peer_common_name
from oim_tpu.serve.engine import (
    DeadlineExpiredError,
    DrainingError,
    Engine,
    EngineFailedError,
    GenRequest,
    QueueFullError,
    RequestFailedError,
)


class StallWatchdog:
    """Driver-side decode stall detector.

    Polls ``engine.watchdog_state()`` every ``interval`` seconds: when
    the driver thread has been blocked in ONE device dispatch/readback
    for longer than ``max(floor_s, multiplier × chunk-wall EWMA)``, the
    device is presumed wedged (TPU init hang, XLA deadlock — exactly
    the BENCH_r05 failure mode, where a hung chip stalled the driver
    silently forever).  On detection: ``on_stall(message)`` runs (the
    server fails in-flight requests fast with kind "stalled" → HTTP 503
    + Retry-After, and flips /healthz unhealthy so the router routes
    around this backend within its probe window), a flight-recorder
    ERROR event is emitted, and ``oim_serve_stalls_total`` counts it.

    No verdict is possible before the first decode chunk completes
    (the EWMA is None) — a cold engine's 20-40 s TPU compiles can never
    false-positive.  If the wait later resolves (transient wedge),
    ``on_clear`` fires once so the server can restore /healthz.
    """

    def __init__(
        self,
        engine: Engine,
        on_stall,
        on_clear=None,
        interval: float = 1.0,
        multiplier: float = 8.0,
        floor_s: float = 10.0,
    ):
        if interval <= 0 or multiplier <= 0 or floor_s <= 0:
            raise ValueError(
                f"need interval, multiplier, floor_s > 0; got "
                f"{interval}, {multiplier}, {floor_s}"
            )
        self.engine = engine
        self.on_stall = on_stall
        self.on_clear = on_clear
        self.interval = interval
        self.multiplier = multiplier
        self.floor_s = floor_s
        self.stalls = 0
        self._fired = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def check(self) -> bool:
        """One poll; returns True while a stall verdict stands.
        Callable directly (tests drive it synchronously)."""
        wait, ewma = self.engine.watchdog_state()
        if wait is None:
            if self._fired:
                # The wedged call returned after all: the stall was
                # transient (preemption burp, one pathological compile).
                self._fired = False
                if self.on_clear is not None:
                    self.on_clear()
            return False
        if ewma is None or self._fired:
            return self._fired
        limit = max(self.floor_s, self.multiplier * ewma)
        if wait <= limit:
            return False
        self._fired = True
        self.stalls += 1
        message = (
            f"decode stall: device wait {wait:.1f}s exceeds "
            f"{limit:.1f}s (chunk EWMA {ewma:.4f}s x {self.multiplier:g}, "
            f"floor {self.floor_s:g}s) — device hang or XLA wedge"
        )
        metrics.SERVE_STALLS.inc(self.engine._engine_label)
        from oim_tpu.common import events

        events.emit(
            "serve.stall",
            component="oim-serve",
            severity=events.ERROR,
            wait_s=round(wait, 1),
            limit_s=round(limit, 1),
            chunk_ewma_s=round(ewma, 4),
        )
        self.on_stall(message)
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.check()

    def start(self) -> "StallWatchdog":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class ServeServer:
    """Owns the engine driver thread and the HTTP listener.

    ``start()`` returns self; ``port`` is the bound port (0 → ephemeral,
    the ``NonBlockingGRPCServer.addr()`` discovery pattern).
    """

    def __init__(
        self,
        engine: Engine,
        host: str = "127.0.0.1",
        port: int = 0,
        ssl_context=None,
        tokenizer=None,
        watchdog_interval: float = 0.0,
        stall_multiplier: float = 8.0,
        stall_floor_s: float = 10.0,
        pool: str = "mixed",
    ):
        """``ssl_context`` (from ``httptls.server_ssl_context``) wraps
        the listener in mTLS: clients must hold a deployment-CA cert or
        the handshake fails before the request is read (the reference's
        mTLS-everywhere stance applied to the serving data plane,
        reference README.md:84-120).  ``tokenizer`` (a
        ``texttok.TextTokenizer``) enables the text surface: requests
        may send ``{"text": ...}`` instead of ``tokens`` and replies
        carry the decoded ``text`` — the engine itself stays
        tokenizer-agnostic.  ``watchdog_interval`` > 0 runs a
        ``StallWatchdog`` beside the driver (oim-serve turns it on;
        embedders/tests opt in): a wedged device fails in-flight
        requests fast and flips /healthz instead of stalling silently.
        ``pool`` is this instance's disaggregation role
        (prefill/decode/mixed, oim-serve --pool): surfaced in /v1/info
        and the load/serve.<id> snapshot so the router partitions the
        fleet (doc/serving.md "Disaggregated prefill/decode")."""
        if pool not in disagg.POOLS:
            raise ValueError(
                f"pool must be one of {disagg.POOLS}, got {pool!r}"
            )
        self.pool = pool
        self.engine = engine
        self.tokenizer = tokenizer
        self.error: str | None = None  # set when the driver thread dies
        # Guards error transitions: the watchdog thread (stall set /
        # clear) races the driver thread (death), and the clear must
        # never clobber a driver-death error that landed between its
        # check and its store.  Bare reads (handlers, the registration
        # health gate) stay lock-free — a reference read is atomic.
        self._error_lock = locksan.new_lock("ServeServer._error_lock")
        # True while self.error came from a stall verdict (clearable);
        # a driver-death error is permanent and must survive a clear.
        self._stall_error = False
        self._stop = threading.Event()
        # On-demand device profiling (ISSUE 18): state dict + worker
        # thread under their OWN lock — /debugz/profile must never
        # touch the engine lock or the error latch, so it stays
        # servable while the backend is wedged.
        self._profile_lock = locksan.new_lock("ServeServer._profile_lock")
        self._profile: dict | None = None
        self._profile_thread: threading.Thread | None = None
        self.watchdog = (
            StallWatchdog(
                engine,
                on_stall=self._on_stall,
                on_clear=self._on_stall_clear,
                interval=watchdog_interval,
                multiplier=stall_multiplier,
                floor_s=stall_floor_s,
            )
            if watchdog_interval > 0
            else None
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # stderr noise → engine stats
                pass

            def _json(
                self, code: int, payload: dict,
                headers: dict | None = None,
            ) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def _retry_after(self) -> dict:
                """Retry-After for 429/503 sheds, from the engine's
                observed marginal token rate (how long the current
                backlog takes to drain)."""
                return {"Retry-After": str(outer.engine.retry_after_s())}

            def _deadline(self, body: dict) -> float | None:
                """Per-request deadline knob: ``deadline_ms`` in the
                body (wins) or the ``x-oim-deadline-ms`` header — a
                RELATIVE millisecond budget, converted to the engine's
                absolute monotonic clock here so client/server clock
                skew never matters."""
                ms = body.get("deadline_ms")
                if ms is None:
                    ms = self.headers.get("x-oim-deadline-ms")
                if ms is None:
                    return None
                ms = float(ms)
                if ms <= 0:
                    raise ValueError(f"deadline_ms must be > 0, got {ms}")
                return time.monotonic() + ms / 1000.0

            def _tenant(self) -> str:
                """The requesting tenant for QoS accounting: the mTLS
                peer CN when this server terminates TLS; on a
                plain-HTTP server (trusted perimeter — typically
                behind the router, which resolves and forwards the
                real identity) the ``x-oim-tenant`` header.  Before
                ISSUE 16 every non-mTLS request collapsed into the
                one anonymous tenant, which made fair-share blind
                behind a router; anon is now an explicit tenant with
                its own (best-effort) tier.  Under TLS the header is
                IGNORED — a cert-bearing client must not re-badge
                itself as someone else's quota."""
                cn = peer_common_name(self)
                if cn:
                    return cn
                if not outer.tls:
                    claimed = (
                        self.headers.get("x-oim-tenant") or ""
                    ).strip()
                    if claimed:
                        return claimed[:128]
                return ""

            def do_GET(self):
                # Serving-plane CN pinning (httptls module docstring):
                # under mTLS the peer must carry a serve./route./user.
                # identity, not merely any deployment-CA cert — parity
                # with the gRPC plane's CN authorization.
                if not check_serving_peer(self):
                    return
                if self.path.split("?", 1)[0] == "/metrics":
                    # Prometheus exposition, shared registry + response
                    # format with the control plane (common/metrics.py).
                    metrics.write_exposition(self)
                    return
                if self.path.split("?", 1)[0] == "/debugz":
                    # Live flight-recorder rings (common/events.py) —
                    # the same surface MetricsServer gives gRPC daemons.
                    from oim_tpu.common import events as events_mod

                    self._json(200, events_mod.snapshot())
                    return
                if self.path.split("?", 1)[0] == "/debugz/requests":
                    # Recently-completed-request ring: per-request
                    # phase breakdowns (queue/admit/prefill/decode/
                    # stream), trace ids, tenant CNs, outcomes — the
                    # slow-request forensics surface (doc/operations.md
                    # "Request forensics").  Merged fleet-wide by the
                    # router at /v1/requests.
                    self._json(200, outer.engine.requests())
                    return
                if self.path.split("?", 1)[0] == "/debugz/profile":
                    # Profiler status / tarball download — own lock
                    # only, BEFORE the error latch like its forensics
                    # siblings above.
                    outer._profile_get(self)
                    return
                if self.path == "/healthz":
                    if outer.error is not None:
                        # A dead driver thread must flip health, or the
                        # orchestrator never restarts a wedged server.
                        self._json(503, {"ok": False, "error": outer.error})
                    else:
                        self._json(200, {"ok": True})
                elif self.path == "/v1/stats":
                    self._json(200, outer.engine.stats())
                elif self.path == "/v1/info":
                    info = outer.engine.info()
                    # Server-level addition: whether the text surface is
                    # live (the engine itself is tokenizer-agnostic).
                    info["tokenizer"] = (
                        outer.tokenizer.path if outer.tokenizer else None
                    )
                    # ... and this instance's disaggregation pool role
                    # (the router partitions the fleet on it).
                    info["pool"] = outer.pool
                    # Live-load mirror of the load/<cn> registry key —
                    # the router refreshes this each probe tick and
                    # surfaces it in its own /v1/stats.
                    info["load"] = outer.load_snapshot()
                    self._json(200, info)
                elif self.path == "/v1/weights":
                    outer._stream_weights(self)
                elif self.path.split("?", 1)[0] == "/v1/kv":
                    outer._stream_kv(self)
                elif self.path.split("?", 1)[0] == "/v1/slot":
                    # Live slot migration (ISSUE 17): a suspended
                    # request's full state, served while draining.
                    outer._stream_slot(self)
                else:
                    self._json(404, {"error": f"no such path {self.path}"})

            def do_PUT(self):
                # KV-ship ingest (serve/disagg.py): the decode side of
                # disaggregated prefill/decode.  Stages host-side only
                # (no device work on handler threads), so it runs even
                # while the queue is deep — but not past a latched
                # error (nothing will ever admit the continuation).
                if not check_serving_peer(self):
                    return
                path = self.path.split("?", 1)[0]
                if path not in ("/v1/kv", "/v1/slot"):
                    self._json(404, {"error": f"no such path {self.path}"})
                    return
                if outer.error is not None:
                    self._json(
                        503, {"error": outer.error}, self._retry_after()
                    )
                    return
                if path == "/v1/slot":
                    # Migration target side (ISSUE 17): stage a shipped
                    # slot for its continuation's kv_import admission.
                    outer._ingest_slot(self)
                else:
                    outer._ingest_kv(self)

            def do_DELETE(self):
                # Release a KV hold (prefill side) or staged import
                # (decode side) — the router's post-ship cleanup.
                # Idempotent: unknown ids answer ok=false, never error
                # (the TTL may have swept first).
                if not check_serving_peer(self):
                    return
                path, _, query = self.path.partition("?")
                if path not in ("/v1/kv", "/v1/slot"):
                    self._json(404, {"error": f"no such path {self.path}"})
                    return
                from urllib.parse import parse_qs

                params = parse_qs(query)
                if path == "/v1/slot" and "rid" in params:
                    # Suspended-slot record release (ISSUE 17): the
                    # router's post-ship cleanup on the draining side.
                    # A staged slot import on the TARGET is a plain
                    # staged KV import — released via /v1/kv?import=.
                    ok = outer.engine.release_migrated(
                        int(params["rid"][0])
                    )
                elif path == "/v1/slot":
                    self._json(400, {"error": "need ?rid="})
                    return
                elif "rid" in params:
                    ok = outer.engine.release_kv_hold(
                        int(params["rid"][0])
                    )
                elif "import" in params:
                    ok = outer.engine.release_kv_import(
                        int(params["import"][0])
                    )
                else:
                    self._json(400, {"error": "need ?rid= or ?import="})
                    return
                self._json(200, {"ok": bool(ok)})

            def _stream(self, req: GenRequest, span) -> None:
                """NDJSON token stream: the engine's on_token callback
                feeds a queue (callbacks must not block the driver
                thread); this handler drains it onto the socket.  A
                client that disconnects mid-stream cancels the request
                (engine.cancel — the slot is freed at the next pipeline
                boundary, abandoned streams stop burning chip time) and
                forfeits the result (engine.forget).
                Ordering holds under the pipelined engine too: chunks
                are processed in dispatch order on the one driver
                thread, so per-request callbacks (and the terminating
                ``(None, None)``) arrive exactly as the serial engine
                would deliver them — tokens merely land one chunk
                later."""
                tokens_q: queue.Queue = queue.Queue()
                decoder = (
                    outer.tokenizer.stream_decoder()
                    if outer.tokenizer is not None
                    else None
                )
                try:
                    rid = outer.engine.submit(
                        req, on_token=lambda t, lp: tokens_q.put((t, lp))
                    )
                except (QueueFullError, DeadlineExpiredError) as exc:
                    span.status = "error: shed"
                    self._json(429, {"error": str(exc)}, self._retry_after())
                    return
                except (DrainingError, EngineFailedError) as exc:
                    span.status = "error: unavailable"
                    self._json(503, {"error": str(exc)}, self._retry_after())
                    return
                try:
                    # Headers inside the try: wfile is unbuffered, so a
                    # client that disconnected right away raises HERE —
                    # the result must still be forgotten, not retained.
                    self.send_response(200)
                    self.send_header("Content-Type", "application/x-ndjson")
                    # Echo the span as a header (the non-stream path puts
                    # it in the JSON body) so streaming callers can
                    # correlate in the merged trace too.
                    self.send_header(
                        "traceparent",
                        tracing.SpanContext(
                            span.trace_id, span.span_id
                        ).traceparent(),
                    )
                    self.end_headers()  # HTTP/1.0: body ends on close
                    while True:
                        try:
                            token, logprob = tokens_q.get(timeout=600)
                        except queue.Empty:
                            # Same situation the non-stream path answers
                            # with 503; the protocol promises a
                            # terminating error line.  Cancel first:
                            # nobody is listening, so the slot must stop
                            # burning chip time.
                            outer.engine.cancel(rid, "stream timed out")
                            outer.engine.forget(rid)
                            span.status = "error: timeout"
                            self.wfile.write(
                                json.dumps(
                                    {"error": f"request {rid} timed out"}
                                ).encode() + b"\n"
                            )
                            return
                        if token is None:
                            break
                        line = {"token": token}
                        if decoder is not None:
                            line["text"] = decoder.push(token)
                        if self.want_logprobs:
                            line["logprob"] = logprob
                        self.wfile.write(
                            (json.dumps(line) + "\n").encode()
                        )
                        self.wfile.flush()
                    try:
                        tokens, lps = outer.engine.result_full(rid, timeout=30)
                        span.attrs["generated"] = len(tokens)
                        # request_id rides the done line so the router's
                        # disaggregation path can address this request's
                        # held KV (GET /v1/kv?rid=...) after the stream.
                        final = {
                            "done": True, "tokens": tokens,
                            "request_id": rid,
                        }
                        if decoder is not None:
                            tail = decoder.flush()
                            if tail:
                                final["text"] = tail
                        if self.want_logprobs:
                            final["logprobs"] = lps
                        self.wfile.write(
                            json.dumps(final).encode() + b"\n"
                        )
                    except RequestFailedError as exc:
                        # Must precede the RuntimeError clause below —
                        # RequestFailedError subclasses RuntimeError and
                        # the migrate marker would otherwise be swallowed
                        # into a plain terminal error line.
                        outer.engine.forget(rid)
                        if exc.kind == "migrated":
                            # Migrate-out drain (ISSUE 17): hand the rid
                            # to the router, which ships this request's
                            # /v1/slot record to a sibling and splices
                            # the continuation onto this client stream.
                            span.status = "migrated"
                            self.wfile.write(
                                json.dumps({
                                    "error": str(exc),
                                    "migrate": True,
                                    "request_id": rid,
                                }).encode() + b"\n"
                            )
                        else:
                            span.status = "error: aborted"
                            self.wfile.write(
                                json.dumps(
                                    {"error": str(exc)}
                                ).encode() + b"\n"
                            )
                    except (RuntimeError, TimeoutError) as exc:
                        outer.engine.forget(rid)
                        span.status = "error: aborted"
                        self.wfile.write(
                            json.dumps({"error": str(exc)}).encode() + b"\n"
                        )
                except (BrokenPipeError, ConnectionResetError):
                    # Client disconnect propagates to the engine: the
                    # request is cancelled (its slot freed at the next
                    # pipeline boundary) instead of decoding to
                    # completion for nobody.
                    outer.engine.cancel(rid, "client disconnected")
                    outer.engine.forget(rid)
                    span.status = "error: client disconnected"

            def do_POST(self):
                if not check_serving_peer(self):
                    return
                if self.path == "/v1/drain":
                    # Migrate-out drain (ISSUE 17): stop admitting and
                    # suspend in-flight work into /v1/slot records.
                    # BEFORE the error latch — draining a wedged
                    # backend is legal and idempotent (everything was
                    # already failed; there is just nothing to
                    # migrate), and the autoscaler's retire path must
                    # never be refused here.
                    outer.begin_drain()
                    self._json(200, {
                        "ok": True,
                        "draining": True,
                        "in_flight": outer.engine.in_flight(),
                    })
                    return
                if self.path.split("?", 1)[0] == "/debugz/profile":
                    # On-demand device profiling (ISSUE 18) — BEFORE
                    # the error latch: capturing a trace from a wedged
                    # backend is precisely the forensic use case.
                    try:
                        length = int(
                            self.headers.get("Content-Length") or 0
                        )
                        doc = (
                            json.loads(self.rfile.read(length))
                            if length else {}
                        )
                    except ValueError:
                        self._json(400, {"error": "malformed JSON body"})
                        return
                    seconds = doc.get("seconds", 2.0)
                    if (
                        not isinstance(seconds, (int, float))
                        or isinstance(seconds, bool)
                        or not seconds > 0
                    ):
                        self._json(400, {
                            "error": "seconds must be a positive number"
                        })
                        return
                    code, payload = outer.start_profile(float(seconds))
                    self._json(code, payload)
                    return
                if outer.error is not None:
                    # Dead driver thread OR a live stall verdict: fail
                    # fast instead of queueing work nothing will drive.
                    # Checked before EVERY engine-touching path — embed
                    # and beam dispatch on the HANDLER thread, so on a
                    # wedged device they would block inside the device
                    # call itself, beyond even the result() timeout.
                    self._json(
                        503, {"error": outer.error}, self._retry_after()
                    )
                    return
                if self.path == "/v1/embed":
                    self._embed_request()
                    return
                if self.path == "/v1/beam":
                    self._beam_request()
                    return
                if self.path in ("/v1/completions", "/v1/chat/completions"):
                    # Same trace-join contract as /v1/generate: the
                    # OpenAI surface gets a server span and the engine
                    # phases parent under it.
                    parent = tracing.parse_traceparent(
                        self.headers.get("traceparent", "")
                    )
                    with tracing.start_span(
                        "serve.completions", component="oim-serve",
                        parent=parent,
                    ) as span:
                        self._completions_request(
                            chat=self.path.endswith("chat/completions"),
                            span=span,
                        )
                    return
                if self.path != "/v1/generate":
                    self._json(404, {"error": f"no such path {self.path}"})
                    return
                # Join the caller's W3C trace (the same propagation the
                # gRPC control plane does via metadata): a workload that
                # traced CSI staging can trace its generations too.
                parent = tracing.parse_traceparent(
                    self.headers.get("traceparent", "")
                )
                with tracing.start_span(
                    "serve.generate", component="oim-serve", parent=parent,
                ) as span:
                    self._generate(span)

            def _completions_request(
                self, chat: bool = False, span=None
            ) -> None:
                """OpenAI-compatible ``/v1/completions``: the shape the
                ecosystem's clients speak, mapped onto the native
                engine.  String prompts/stops need the server-side
                tokenizer (--tokenizer-dir); token-list prompts work on
                any instance.  ``n`` choices run as n engine requests
                (seeds seed+i); ``stream`` is SSE with OpenAI chunk
                objects and a final ``data: [DONE]``.  Stop strings are
                applied by post-hoc truncation of the decoded text —
                exact for completed responses; streaming rejects
                ``stop`` rather than emit text past the boundary."""
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    if chat:
                        # /v1/chat/completions: messages rendered
                        # through the tokenizer's OWN chat template
                        # (imported next to the weights).
                        if outer.tokenizer is None:
                            raise ValueError(
                                "chat completions need a server-side "
                                "tokenizer (oim-serve --tokenizer-dir)"
                            )
                        messages = body.get("messages")
                        if not isinstance(messages, list) or not messages:
                            raise ValueError("messages must be a non-empty list")
                        tokens = outer.tokenizer.apply_chat_template(
                            messages
                        )
                    else:
                        prompt = body.get("prompt", "")
                        if isinstance(prompt, list):
                            tokens = [int(t) for t in prompt]
                        else:
                            if outer.tokenizer is None:
                                raise ValueError(
                                    "string prompts need a server-side "
                                    "tokenizer (oim-serve --tokenizer-dir); "
                                    "send a token-id list instead"
                                )
                            tokens = outer.tokenizer.encode(str(prompt))
                    stops = body.get("stop") or []
                    if isinstance(stops, str):
                        stops = [stops]
                    if stops and outer.tokenizer is None:
                        raise ValueError(
                            "stop strings need a server-side tokenizer"
                        )
                    n = int(body.get("n", 1))
                    if not 1 <= n <= 8:
                        raise ValueError("n must be in [1, 8]")
                    stream = bool(body.get("stream"))
                    if stream and (stops or n != 1):
                        raise ValueError(
                            "stream=true supports neither stop strings "
                            "nor n > 1"
                        )
                    if stream and outer.tokenizer is None:
                        # Bare token ids concatenated into the OpenAI
                        # text field would be unparseable.
                        raise ValueError(
                            "stream=true needs a server-side tokenizer "
                            "(oim-serve --tokenizer-dir)"
                        )
                    temperature = float(body.get("temperature", 1.0))
                    seed = int(body.get("seed", 0))
                    deadline = self._deadline(body)

                    def req_for(i):
                        return GenRequest(
                            tokens=tokens,
                            max_new_tokens=int(body.get("max_tokens", 16)),
                            temperature=temperature,
                            seed=seed + i,
                            deadline=deadline,
                            span=(
                                tracing.SpanContext(
                                    span.trace_id, span.span_id
                                )
                                if span is not None else None
                            ),
                            tenant=self._tenant(),
                            eos_id=(
                                outer.tokenizer.eos_id
                                if outer.tokenizer is not None
                                else None
                            ),
                            top_p=(
                                float(body["top_p"])
                                if body.get("top_p") is not None
                                else None
                            ),
                            presence_penalty=float(
                                body.get("presence_penalty", 0.0)
                            ),
                            frequency_penalty=float(
                                body.get("frequency_penalty", 0.0)
                            ),
                        )

                    rids = []
                    if stream:
                        self._completions_stream(req_for(0), body, chat)
                        return
                    for i in range(n):
                        rids.append(outer.engine.submit(req_for(i)))
                except (QueueFullError, DeadlineExpiredError) as exc:
                    self._forget_all(rids, cancel="batch sibling shed")
                    self._json(
                        429, {"error": {"message": str(exc)}},
                        self._retry_after(),
                    )
                    return
                except (DrainingError, EngineFailedError) as exc:
                    self._forget_all(rids, cancel="batch sibling shed")
                    self._json(
                        503, {"error": {"message": str(exc)}},
                        self._retry_after(),
                    )
                    return
                except (KeyError, TypeError, ValueError) as exc:
                    self._json(400, {"error": {"message": str(exc)}})
                    return
                choices = []
                completion_tokens = 0
                for i, rid in enumerate(rids):
                    try:
                        out = outer.engine.result(rid, timeout=600)
                    except TimeoutError:
                        self._forget_all(
                            rids[i:], cancel="client wait timed out"
                        )
                        self._json(
                            503,
                            {"error": {"message": f"{rid} timed out"}},
                        )
                        return
                    except RequestFailedError as exc:
                        self._forget_all(
                            rids[i + 1:], cancel="batch sibling failed"
                        )
                        code = {
                            "deadline_queue": 429,
                            "deadline": 504,
                            "stalled": 503,
                        }.get(exc.kind, 500)
                        headers = (
                            self._retry_after()
                            if code in (429, 503) else None
                        )
                        self._json(
                            code, {"error": {"message": str(exc)}}, headers
                        )
                        return
                    except RuntimeError as exc:
                        self._forget_all(
                            rids[i + 1:], cancel="batch sibling failed"
                        )
                        self._json(500, {"error": {"message": str(exc)}})
                        return
                    completion_tokens += len(out)
                    finish = (
                        "length"
                        if len(out) >= int(body.get("max_tokens", 16))
                        else "stop"
                    )
                    choice = {
                        "index": i,
                        "finish_reason": finish,
                        "logprobs": None,
                    }
                    if outer.tokenizer is not None:
                        text = outer.tokenizer.decode(out)
                        for s in stops:
                            cut = text.find(s)
                            if cut >= 0:
                                text = text[:cut]
                                choice["finish_reason"] = "stop"
                        if chat:
                            choice["message"] = {
                                "role": "assistant", "content": text,
                            }
                        else:
                            choice["text"] = text
                    else:
                        choice["text"] = ""
                        choice["tokens"] = out
                    choices.append(choice)
                self._json(200, {
                    "id": f"{'chatcmpl' if chat else 'cmpl'}-{rids[0]}",
                    "object": (
                        "chat.completion" if chat else "text_completion"
                    ),
                    "created": int(time.time()),
                    "model": body.get("model", "oim-tpu"),
                    "choices": choices,
                    "usage": {
                        "prompt_tokens": len(tokens),
                        "completion_tokens": completion_tokens,
                        "total_tokens": len(tokens) + completion_tokens,
                    },
                })

            def _forget_all(self, rids, cancel: str | None = None) -> None:
                """Release engine results for every rid in ``rids`` —
                an n>1 request failing partway must not strand the
                other choices' results in the daemon forever.  With
                ``cancel``, each rid is cancelled first: the client is
                getting an error for the whole batch, so siblings still
                queued or decoding must stop burning chip time, not
                run to completion for nobody."""
                for rid in rids:
                    if cancel is not None:
                        outer.engine.cancel(rid, cancel)
                    outer.engine.forget(rid)

            def _completions_stream(
                self, req: GenRequest, body, chat: bool = False
            ) -> None:
                """SSE stream of OpenAI completion (or chat-completion
                delta) chunks."""
                tokens_q: queue.Queue = queue.Queue()
                decoder = outer.tokenizer.stream_decoder()  # required
                rid = outer.engine.submit(
                    req, on_token=lambda t, lp: tokens_q.put((t, lp))
                )
                created = int(time.time())

                def chunk(text, finish=None):
                    if chat:
                        choice = {
                            "index": 0,
                            "delta": (
                                {"role": "assistant", "content": text}
                                if text or finish is None else {}
                            ),
                            "finish_reason": finish,
                        }
                    else:
                        choice = {
                            "index": 0,
                            "text": text,
                            "finish_reason": finish,
                            "logprobs": None,
                        }
                    return (
                        "data: " + json.dumps({
                            "id": f"{'chatcmpl' if chat else 'cmpl'}-{rid}",
                            "object": (
                                "chat.completion.chunk"
                                if chat else "text_completion"
                            ),
                            "created": created,
                            "model": body.get("model", "oim-tpu"),
                            "choices": [choice],
                        }) + "\n\n"
                    ).encode()

                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    emitted = 0
                    while True:
                        token, _lp = tokens_q.get(timeout=600)
                        if token is None:
                            tail = decoder.flush()
                            final = (
                                "length"
                                if emitted >= req.max_new_tokens
                                else "stop"
                            )
                            if tail:
                                self.wfile.write(chunk(tail))
                            self.wfile.write(chunk("", finish=final))
                            self.wfile.write(b"data: [DONE]\n\n")
                            return
                        emitted += 1
                        delta = decoder.push(token)
                        if delta:
                            self.wfile.write(chunk(delta))
                except queue.Empty:
                    # Same situation the non-stream path answers with
                    # 503: emit a terminal error event — a silent close
                    # would be indistinguishable from completion.
                    outer.engine.cancel(rid, "stream timed out")
                    outer.engine.forget(rid)
                    try:
                        self.wfile.write(
                            b'data: ' + json.dumps(
                                {"error": {
                                    "message": f"request {rid} timed out"
                                }}
                            ).encode() + b"\n\n"
                        )
                    except (BrokenPipeError, ConnectionResetError):
                        pass
                except (BrokenPipeError, ConnectionResetError):
                    outer.engine.cancel(rid, "client disconnected")
                    outer.engine.forget(rid)

            def _embed_request(self) -> None:
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    vec = outer.engine.embed(
                        self._prompt_tokens(body)
                    )
                except (KeyError, TypeError, ValueError) as exc:
                    self._json(400, {"error": str(exc)})
                    return
                self._json(200, {"embedding": vec, "dim": len(vec)})

            def _beam_request(self) -> None:
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    toks, score = outer.engine.beam(
                        self._prompt_tokens(body),
                        max_new_tokens=int(body.get("max_new_tokens", 16)),
                        beam_size=int(body.get("beam_size", 4)),
                        alpha=float(body.get("alpha", 0.6)),
                        eos_id=self._default_eos(body),
                    )
                except (KeyError, TypeError, ValueError) as exc:
                    self._json(400, {"error": str(exc)})
                    return
                payload = {"tokens": toks, "score": score}
                if outer.tokenizer is not None:
                    payload["text"] = outer.tokenizer.decode(toks)
                self._json(200, payload)

            def _prompt_tokens(self, body: dict) -> list[int]:
                """Prompt ids from ``tokens`` or (with a tokenizer)
                ``text`` — exactly one of the two."""
                if "text" in body and "tokens" in body:
                    raise ValueError("send either 'tokens' or 'text', not both")
                if "text" in body:
                    if outer.tokenizer is None:
                        raise ValueError(
                            "'text' needs a server-side tokenizer "
                            "(oim-serve --tokenizer-dir); this instance "
                            "speaks token ids only"
                        )
                    return outer.tokenizer.encode(str(body["text"]))
                return [int(t) for t in body["tokens"]]

            def _default_eos(self, body: dict) -> int | None:
                """Explicit eos_id wins; text-mode requests default to
                the tokenizer's EOS (a text caller means "a model turn",
                not "exactly max_new_tokens")."""
                if body.get("eos_id") is not None:
                    return int(body["eos_id"])
                if "text" in body and outer.tokenizer is not None:
                    return outer.tokenizer.eos_id
                return None

            def _generate(self, span) -> None:
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    req = GenRequest(
                        tokens=self._prompt_tokens(body),
                        max_new_tokens=int(body.get("max_new_tokens", 16)),
                        temperature=float(body.get("temperature", 0.0)),
                        seed=int(body.get("seed", 0)),
                        eos_id=self._default_eos(body),
                        stop_ids=tuple(
                            int(t) for t in body.get("stop_ids", ())
                        ),
                        top_p=(
                            float(body["top_p"])
                            if body.get("top_p") is not None
                            else None
                        ),
                        min_p=float(body.get("min_p", 0.0)),
                        repetition_penalty=float(
                            body.get("repetition_penalty", 1.0)
                        ),
                        presence_penalty=float(
                            body.get("presence_penalty", 0.0)
                        ),
                        frequency_penalty=float(
                            body.get("frequency_penalty", 0.0)
                        ),
                        cache_prefix=bool(body.get("cache_prefix")),
                        # Disaggregated prefill/decode (serve/disagg.py):
                        # hold_kv marks a prefill leg (KV retained for
                        # GET /v1/kv), kv_import a decode continuation
                        # (resume from a staged PUT /v1/kv ingest).
                        hold_kv=bool(body.get("hold_kv")),
                        kv_import=(
                            int(body["kv_import"])
                            if body.get("kv_import") is not None
                            else None
                        ),
                        # Global emission index of this leg's first
                        # sampled token: a migrated/spliced continuation
                        # passes len(already-emitted) so its PRNG keys
                        # line up with an undisturbed solo run.
                        sample_base=int(body.get("sample_base", 0)),
                        deadline=self._deadline(body),
                        # The engine parents its phase spans on the
                        # server span: one trace id from the router's
                        # ingress down to per-chunk decode spans.
                        span=tracing.SpanContext(
                            span.trace_id, span.span_id
                        ),
                        tenant=self._tenant(),
                    )
                    span.attrs.update(
                        prompt_tokens=len(req.tokens),
                        max_new_tokens=req.max_new_tokens,
                        stream=bool(body.get("stream")),
                    )
                    self.want_logprobs = bool(body.get("logprobs"))
                    if body.get("stream"):
                        self._stream(req, span)
                        return
                    rid = outer.engine.submit(req)
                except (QueueFullError, DeadlineExpiredError) as exc:
                    # Shed: queue at capacity, or the deadline budget
                    # was already gone — 429 with a drain-rate hint.
                    span.status = "error: shed"
                    self._json(429, {"error": str(exc)}, self._retry_after())
                    return
                except (DrainingError, EngineFailedError) as exc:
                    span.status = "error: unavailable"
                    self._json(503, {"error": str(exc)}, self._retry_after())
                    return
                except (KeyError, TypeError, ValueError) as exc:
                    span.status = "error: bad request"
                    self._json(400, {"error": str(exc)})
                    return
                try:
                    tokens, lps = outer.engine.result_full(rid, timeout=600)
                except TimeoutError:
                    # Clean 503 instead of a dropped socket; cancel stops
                    # the slot burning for a client that stopped waiting,
                    # forget() frees the result if it lands anyway — a
                    # flaky client must not grow the daemon's memory.
                    outer.engine.cancel(rid, "server-side wait timed out")
                    outer.engine.forget(rid)
                    span.status = "error: timeout"
                    self._json(503, {"error": f"request {rid} timed out"})
                    return
                except RequestFailedError as exc:
                    span.status = f"error: {exc.kind}"
                    if exc.kind == "deadline_queue":
                        # Shed before touching a slot: retryable, cheap.
                        self._json(
                            429, {"error": str(exc)}, self._retry_after()
                        )
                    elif exc.kind == "deadline":
                        self._json(504, {"error": str(exc)})
                    elif exc.kind == "stalled":
                        # Watchdog failed it fast; another replica can
                        # serve it — distinct from a driver-death 500.
                        self._json(
                            503, {"error": str(exc)}, self._retry_after()
                        )
                    elif exc.kind == "migrated":
                        # Suspended by a migrate-out drain.  Stream
                        # splicing is where live handoff happens;
                        # non-stream callers just retry — the router's
                        # failover resubmits on a sibling, which is
                        # token-identical from scratch (same seed).
                        self._json(
                            503, {"error": str(exc)}, self._retry_after()
                        )
                    else:  # aborted / cancelled
                        self._json(500, {"error": str(exc)})
                    return
                except RuntimeError as exc:  # aborted: driver thread died
                    span.status = "error: aborted"
                    self._json(500, {"error": str(exc)})
                    return
                span.attrs["generated"] = len(tokens)
                payload = {
                    "tokens": tokens,
                    "request_id": rid,
                    # Echo the span so callers can correlate this
                    # generation in the merged trace (oimctl trace).
                    "traceparent": tracing.SpanContext(
                        span.trace_id, span.span_id
                    ).traceparent(),
                }
                if outer.tokenizer is not None:
                    payload["text"] = outer.tokenizer.decode(tokens)
                if self.want_logprobs:
                    payload["logprobs"] = lps
                self._json(200, payload)

        if ssl_context is not None:
            from oim_tpu.serve.httptls import TLSThreadingHTTPServer

            self._httpd = TLSThreadingHTTPServer(
                (host, port), Handler, ssl_context
            )
        else:
            self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.tls = ssl_context is not None
        self.host, self.port = self._httpd.server_address[:2]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._driver_thread = threading.Thread(target=self._drive, daemon=True)

    def _on_stall(self, message: str) -> None:
        """Watchdog verdict: fail in-flight requests fast with the
        retryable "stalled" kind (HTTP 503 + Retry-After) and flip
        /healthz unhealthy so the router routes around this backend."""
        with self._error_lock:
            if self.error is None:
                self.error = message
                self._stall_error = True
        self.engine.abort(message, kind="stalled")

    def _on_stall_clear(self) -> None:
        """The wedged device call returned: restore /healthz (only if
        the stall was what broke it — a dead driver thread stays dead;
        the explicit flag, not the message text, is what distinguishes
        the two)."""
        with self._error_lock:
            if self._stall_error:
                self.error = None
                self._stall_error = False

    def _stream_weights(self, handler) -> None:
        """Stream the engine's params over HTTP for peer bring-up
        (``GET /v1/weights``): 8-byte big-endian manifest length, JSON
        manifest, then each leaf's raw bytes in manifest order.  Leaves
        are pulled off the device one at a time while streaming, so
        host memory holds one array, not the model.  Refused (503)
        while the server's error is latched: a device_get against a
        wedged device would hang this handler thread inside the device
        call."""
        import struct

        import numpy as np

        if self.error is not None:
            handler._json(
                503, {"error": f"weights unavailable: {self.error}"}
            )
            return
        params = self.engine.params
        names = sorted(params)
        manifest = []
        total = 0
        for name in names:
            arr = params[name]
            manifest.append(
                {
                    "name": name,
                    "dtype": str(arr.dtype),
                    "shape": [int(d) for d in arr.shape],
                }
            )
            total += int(arr.nbytes)
        manifest_bytes = json.dumps(manifest, separators=(",", ":")).encode()
        handler.send_response(200)
        handler.send_header("Content-Type", "application/octet-stream")
        handler.send_header(
            "Content-Length", str(8 + len(manifest_bytes) + total)
        )
        handler.end_headers()
        try:
            handler.wfile.write(struct.pack(">Q", len(manifest_bytes)))
            handler.wfile.write(manifest_bytes)
            chunk = 4 << 20
            for name in names:
                # ascontiguousarray: the byte order must match the
                # manifest's C-order shape contract regardless of any
                # device-side layout; the uint8 reinterpret-view then
                # streams those bytes with ZERO extra host copies
                # (tobytes would double the transient footprint per
                # leaf — and the big leaves are model-embedding sized).
                host = np.ascontiguousarray(np.asarray(params[name]))
                flat = host.reshape(-1).view(np.uint8)
                for off in range(0, flat.size, chunk):
                    handler.wfile.write(flat[off:off + chunk].data)
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            # Peer gave up mid-fetch (its own retry re-pulls); nothing
            # here holds state worth cleaning up.
            return

    def load_snapshot(self) -> dict:
        """The ``load/serve.<id>`` value: the engine's live pressure
        plus the server-level pool role (the engine is pool-agnostic
        the way it is tokenizer-agnostic).  Published each heartbeat
        by ServeRegistration and mirrored under /v1/info "load"."""
        return dict(self.engine.load(), pool=self.pool)

    def _stream_kv(self, handler) -> None:
        """Stream one held request's KV state (``GET /v1/kv?rid=N``) or
        one resident prefix entry (``GET /v1/kv?prefix=<digest>``,
        serve/disagg.py): the /v1/weights framing — 8-byte big-endian
        manifest length, JSON manifest, raw leaves in manifest order —
        applied to paged-KV blocks.  Refused 503 while the error latch
        stands (the weights rule: no device reads against a wedged
        chip), 404/409 when there is nothing eligible to export (the
        router falls back to splice/prefill recompute)."""
        import struct
        from urllib.parse import parse_qs

        import numpy as np

        if self.error is not None:
            handler._json(
                503, {"error": f"KV export unavailable: {self.error}"}
            )
            return
        params = parse_qs(handler.path.partition("?")[2])
        prefix = (params.get("prefix") or [""])[0]
        if not prefix:
            try:
                rid = int(params["rid"][0])
            except (KeyError, ValueError):
                handler._json(
                    400,
                    {"error": "need ?rid=<request id> or "
                              "?prefix=<digest>"},
                )
                return
        try:
            if prefix:
                manifest, arrays = self.engine.export_kv_prefix(prefix)
            else:
                manifest, arrays = self.engine.export_kv(rid)
        except disagg.KvIneligibleError as exc:
            code = 404 if (
                "no held KV" in str(exc) or "no resident prefix" in str(exc)
            ) else 409
            handler._json(code, {"error": str(exc)})
            return
        manifest_bytes = json.dumps(
            manifest, separators=(",", ":")
        ).encode()
        total = sum(int(a.nbytes) for a in arrays)
        handler.send_response(200)
        handler.send_header("Content-Type", "application/octet-stream")
        handler.send_header(
            "Content-Length", str(8 + len(manifest_bytes) + total)
        )
        handler.end_headers()
        try:
            handler.wfile.write(struct.pack(">Q", len(manifest_bytes)))
            handler.wfile.write(manifest_bytes)
            for arr in arrays:
                # Zero-copy uint8 reinterpret view, the weights-stream
                # discipline — KV for a long prompt is MBs per ship.
                flat = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
                handler.wfile.write(flat.data)
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            return  # the router's ship fallback owns recovery

    def begin_drain(self) -> None:
        """Enter migrate-out drain (``POST /v1/drain``, ISSUE 17):
        stop admitting new work and have the driver suspend every
        in-flight request into a ``/v1/slot`` record at the next step
        boundary.  Idempotent; any thread."""
        self.engine.begin_migrate_out()

    def _stream_slot(self, handler) -> None:
        """Export one suspended slot (``GET /v1/slot?rid=``, ISSUE 17)
        over the PR 12 wire framing: 8-byte BE manifest length, JSON
        manifest (with the ``"slot"`` continuation branch), raw leaves
        in manifest order.  Refused 503 while the error latch stands;
        404 when the rid has no migrated record (released, TTL-swept,
        or never suspended here), 409 when it exists but cannot ship
        (kv4/dense) — the router falls back to splice-recompute."""
        import struct
        from urllib.parse import parse_qs

        import numpy as np

        if self.error is not None:
            handler._json(
                503, {"error": f"slot export unavailable: {self.error}"}
            )
            return
        params = parse_qs(handler.path.partition("?")[2])
        try:
            rid = int(params["rid"][0])
        except (KeyError, ValueError):
            handler._json(400, {"error": "need ?rid=<request id>"})
            return
        try:
            manifest, arrays = self.engine.export_slot(rid)
        except disagg.KvIneligibleError as exc:
            code = 404 if "no migrated slot" in str(exc) else 409
            handler._json(code, {"error": str(exc)})
            return
        manifest_bytes = json.dumps(
            manifest, separators=(",", ":")
        ).encode()
        total = sum(int(a.nbytes) for a in arrays)
        handler.send_response(200)
        handler.send_header("Content-Type", "application/octet-stream")
        handler.send_header(
            "Content-Length", str(8 + len(manifest_bytes) + total)
        )
        handler.end_headers()
        try:
            handler.wfile.write(struct.pack(">Q", len(manifest_bytes)))
            handler.wfile.write(manifest_bytes)
            for arr in arrays:
                flat = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
                handler.wfile.write(flat.data)
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            return  # the router's migrate fallback owns recovery

    def _ingest_slot(self, handler) -> None:
        """Stage one shipped slot (``PUT /v1/slot``, ISSUE 17): the
        KV payload rides the ordinary staged-import path (same
        geometry/capacity ladder as ``PUT /v1/kv`` — 409 mismatch,
        429 + Retry-After exhaustion), and the manifest's ``"slot"``
        branch is echoed back so the router can build the
        continuation request: {"import_id", "rows", "slot"}."""
        try:
            length = int(handler.headers.get("Content-Length", "0"))
            body = handler.rfile.read(length)
            manifest, data = disagg.unpack_transfer(body)
            import_id, rows, slot_meta = self.engine.import_slot(
                manifest, data
            )
        except disagg.KvCapacityError as exc:
            handler._json(429, {"error": str(exc)}, handler._retry_after())
            return
        except (disagg.KvGeometryError, disagg.KvIneligibleError) as exc:
            handler._json(409, {"error": str(exc)})
            return
        except (KeyError, TypeError, ValueError) as exc:
            handler._json(400, {"error": str(exc)})
            return
        handler._json(
            200, {"import_id": import_id, "rows": rows, "slot": slot_meta}
        )

    def _ingest_kv(self, handler) -> None:
        """Stage one shipped KV state (``PUT /v1/kv``): parse the
        transfer, geometry-validate, reserve pool blocks — answering
        409 on mismatch (never coerce) and 429 + Retry-After on block
        exhaustion (capacity backpressure, the admission planner's
        stance).  A request-hold transfer replies {"import_id",
        "rows"} for the continuation's ``kv_import`` field; a PREFIX
        transfer (manifest carries "prefix", ISSUE 14) installs a
        refcounted prefix-cache entry instead and replies {"prefix",
        "rows"} (rows 0 = already resident, idempotent)."""
        try:
            length = int(handler.headers.get("Content-Length", "0"))
            body = handler.rfile.read(length)
            manifest, data = disagg.unpack_transfer(body)
            if manifest.get("prefix"):
                digest, rows = self.engine.import_kv_prefix(
                    manifest, data
                )
                handler._json(200, {"prefix": digest, "rows": rows})
                return
            import_id, rows = self.engine.import_kv(manifest, data)
        except disagg.KvCapacityError as exc:
            handler._json(429, {"error": str(exc)}, handler._retry_after())
            return
        except (disagg.KvGeometryError, disagg.KvIneligibleError) as exc:
            handler._json(409, {"error": str(exc)})
            return
        except (KeyError, TypeError, ValueError) as exc:
            handler._json(400, {"error": str(exc)})
            return
        handler._json(200, {"import_id": import_id, "rows": rows})

    # -- on-demand device profiling (ISSUE 18) -----------------------------

    def start_profile(self, seconds: float) -> tuple[int, dict]:
        """Start a bounded ``jax.profiler`` trace into the flight dir;
        returns (http_code, payload).  One at a time: 409 while a
        capture runs.  The worker thread tars the trace directory when
        the window closes so GET ?download=1 can stream one artifact.
        Own lock only — never the engine lock or the error latch."""
        seconds = max(0.05, min(float(seconds), 60.0))
        with self._profile_lock:
            if (
                self._profile is not None
                and self._profile.get("state") == "running"
            ):
                return 409, {
                    "error": "a profile capture is already running",
                    "profile": dict(self._profile),
                }
            out_dir = os.path.join(
                events.flight_dir(),
                f"oim-profile-{os.getpid()}-{int(time.time() * 1000)}",
            )
            self._profile = {
                "state": "running",
                "dir": out_dir,
                "seconds": seconds,
                "started_ts": time.time(),
                "tar": "",
                "tar_bytes": 0,
                "error": "",
            }
            # Old worker (if any) has finished — its state says so;
            # join it before replacing the handle so stop() only ever
            # has one thread to reap.
            if self._profile_thread is not None:
                self._profile_thread.join(timeout=5)
            self._profile_thread = threading.Thread(
                target=self._run_profile,
                args=(out_dir, seconds),
                name="serve-profile",
                daemon=True,
            )
            self._profile_thread.start()
            return 202, {"ok": True, "profile": dict(self._profile)}

    def _run_profile(self, out_dir: str, seconds: float) -> None:
        try:
            # Deferred import: the profiler drags in TensorBoard-ish
            # machinery that a serving daemon should only pay for when
            # an operator actually asks for a trace.
            import jax.profiler as _profiler

            os.makedirs(out_dir, exist_ok=True)
            _profiler.start_trace(out_dir)
            try:
                # Server shutdown aborts the window early rather than
                # holding stop() hostage for the full duration.
                self._stop.wait(seconds)
            finally:
                _profiler.stop_trace()
            tar_path = out_dir + ".tar.gz"
            with tarfile.open(tar_path, "w:gz") as tar:
                tar.add(out_dir, arcname=os.path.basename(out_dir))
            size = os.path.getsize(tar_path)
            with self._profile_lock:
                if self._profile is not None:
                    self._profile.update(
                        state="done", tar=tar_path, tar_bytes=size,
                    )
            events.emit(
                "serve.profile",
                component="serve",
                subject=os.path.basename(tar_path),
                seconds=seconds,
                path=tar_path,
                bytes=size,
            )
        except Exception as exc:
            with self._profile_lock:
                if self._profile is not None:
                    self._profile.update(
                        state="failed",
                        error=f"{type(exc).__name__}: {exc}",
                    )

    def _profile_get(self, handler) -> None:
        query = urllib.parse.parse_qs(
            urllib.parse.urlsplit(handler.path).query
        )
        with self._profile_lock:
            doc = dict(self._profile) if self._profile is not None else None
        if "download" not in query:
            handler._json(200, {"profile": doc})
            return
        if doc is None or doc["state"] != "done":
            code = 409 if doc is not None and (
                doc["state"] == "running"
            ) else 404
            handler._json(code, {
                "error": "no finished profile to download",
                "profile": doc,
            })
            return
        try:
            with open(doc["tar"], "rb") as f:
                body = f.read()
        except OSError as exc:
            handler._json(410, {"error": f"trace artifact gone: {exc}"})
            return
        handler.send_response(200)
        handler.send_header("Content-Type", "application/gzip")
        handler.send_header("Content-Length", str(len(body)))
        handler.send_header(
            "Content-Disposition",
            f'attachment; filename="{os.path.basename(doc["tar"])}"',
        )
        handler.end_headers()
        handler.wfile.write(body)

    def _drive(self) -> None:
        while not self._stop.is_set():
            try:
                if self.engine.pending():
                    self.engine.step()
                else:
                    time.sleep(0.005)
            except Exception as exc:  # driver death = service death
                message = f"{type(exc).__name__}: {exc}"
                with self._error_lock:
                    # Overwrite even a stall verdict: a dead driver is
                    # the stronger (and permanent) condition.
                    self.error = message
                    self._stall_error = False
                # The engine already latched the crash and failed every
                # waiter inside step(); this abort is a no-op backstop.
                self.engine.abort(message)
                return

    def start(self) -> "ServeServer":
        self._http_thread.start()
        self._driver_thread.start()
        if self.watchdog is not None:
            self.watchdog.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.watchdog is not None:
            self.watchdog.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        # Join the listener as well as the driver: shutdown() handshakes
        # with serve_forever, but returning before the loop actually
        # exits lets a quick rebind of the same port race the old
        # listener (rolling-restart tests bind back-to-back).
        if self._http_thread.is_alive():
            self._http_thread.join(timeout=10)
        self._driver_thread.join(timeout=10)
        # _stop above aborts an in-flight capture's wait; reap the
        # worker so no profile thread outlives the server.
        with self._profile_lock:
            profile_thread = self._profile_thread
            self._profile_thread = None
        if profile_thread is not None:
            profile_thread.join(timeout=10)
