"""Runtime recompile sentinel (ISSUE 18): the jit-guard invariant
enforced in production.

``tests/test_jit_guard.py`` proves the mechanism at test time — one
``jax.monitoring`` backend-compile duration event fires per XLA
compilation, and a warm serve engine pays zero of them under live
traffic.  This module installs the same listener in a *serving daemon*
so the invariant is watched on every live backend instead of only in
CI:

- every compile increments ``oim_xla_compiles_total`` and observes
  ``oim_xla_compile_seconds`` (warmup compiles included — the plateau
  after warmup IS the signal);
- after an engine's warmup finishes it **arms** itself here
  (``Engine.warmup`` calls :func:`arm`), and from then on any compile
  emits a ``serve.recompile`` WARNING flight-recorder event carrying
  the engine's active request/phase context — on a real TPU that
  compile is 20-40 s of dead air mid-stream, and the event names the
  request that was on the device when it happened.

The listener runs on whatever thread XLA compiles on — possibly the
engine driver thread itself, mid-dispatch, while it holds the engine
lock.  It must therefore never take any engine lock: the request
context is read through ``engine._sentinel_ctx``, a small dict the
driver *replaces* (never mutates) at phase boundaries, so a plain
attribute read is always a consistent snapshot.

Process-global by necessity (``jax.monitoring`` listeners are
process-global and cannot be unregistered): :func:`install` is
idempotent, arming is per-engine via a WeakSet, and warmups anywhere in
the process suppress event emission (a second engine warming in the
same process legitimately compiles; its compiles are not another
engine's recompiles).
"""

from __future__ import annotations

import threading
import weakref

from oim_tpu.common import events as _events
from oim_tpu.common import metrics as _metrics

# One event per XLA backend compilation (same constant the jit-guard
# suite pins against).
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_state = {"installed": False}
_armed: "weakref.WeakSet" = weakref.WeakSet()
# Engines currently inside warmup() anywhere in this process; while
# nonzero, compiles are counted but serve.recompile stays quiet.
_warming = [0]


def install() -> bool:
    """Register the backend-compile listener (idempotent — listeners
    cannot be unregistered, so exactly one is ever installed).  Called
    at daemon init by oim-serve; tests call it directly.  Without this,
    :func:`arm` is inert — an embedder that never installs the sentinel
    sees zero behavior change."""
    with _lock:
        if _state["installed"]:
            return False
        # Deferred so importing this module (e.g. for arm/disarm from
        # the engine) never forces jax extension state to initialise.
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _state["installed"] = True
        return True


def installed() -> bool:
    with _lock:
        return _state["installed"]


def arm(engine) -> None:
    """Latch steady state for ``engine``: from now on, any XLA compile
    in this process emits a ``serve.recompile`` WARNING with the
    engine's active request context.  ``Engine.warmup`` calls this as
    its final act; held weakly, so a dropped engine disarms itself."""
    with _lock:
        _armed.add(engine)


def disarm(engine) -> None:
    with _lock:
        _armed.discard(engine)


def armed(engine) -> bool:
    with _lock:
        return engine in _armed


def begin_warmup() -> None:
    """Engine.warmup() brackets its body with begin/end so a second
    engine warming in an already-armed process (tests, multi-engine
    embedders) does not spray serve.recompile events for its own
    legitimate first compiles."""
    with _lock:
        _warming[0] += 1


def end_warmup() -> None:
    with _lock:
        _warming[0] = max(0, _warming[0] - 1)


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if event != _COMPILE_EVENT:
        return
    _metrics.XLA_COMPILES.inc()
    _metrics.XLA_COMPILE_SECONDS.observe(duration)
    with _lock:
        if _warming[0] > 0:
            return
        engines = list(_armed)
    for engine in engines:
        # Lock-free context read: the driver replaces _sentinel_ctx
        # wholesale at phase boundaries (atomic under the GIL).
        ctx = getattr(engine, "_sentinel_ctx", None) or {}
        try:
            engine.recompiles += 1
        except Exception:
            pass
        _events.emit(
            "serve.recompile",
            component="serve",
            severity=_events.WARNING,
            subject=str(getattr(engine, "_engine_label", "")),
            duration_s=round(float(duration), 6),
            **ctx,
        )
