"""Text surface for the serving API: an HF-tokenizer wrapper.

The engine is tokenizer-agnostic by design (token ids in, token ids
out — the same stance as the reference control plane being
filesystem-agnostic), but a deployment serving an imported HF
checkpoint (cli/import_hf_main.py) has the model's tokenizer sitting
right next to the weights.  ``--tokenizer-dir`` loads it here and the
HTTP layer gains ``{"text": ...}`` requests and decoded-text replies —
the engine itself never sees a string.

Incremental decoding: token-at-a-time ``decode`` is wrong for BPE
(multi-byte/multi-token characters), so streaming uses
``StreamDecoder`` — decode the full generated-so-far sequence, emit the
suffix, and hold back while the tail ends in an incomplete UTF-8
replacement char.
"""

from __future__ import annotations


class TextTokenizer:
    """Lazy wrapper over ``transformers.AutoTokenizer``.

    transformers is an OPTIONAL runtime dep (runtime-deps.csv: the HF
    interop scope); constructing this without it raises a clear error
    naming the missing piece rather than an ImportError five frames
    deep.
    """

    def __init__(self, path: str):
        try:
            from transformers import AutoTokenizer
        except ImportError as exc:  # pragma: no cover - env-dependent
            raise RuntimeError(
                "--tokenizer-dir needs the 'transformers' package "
                "(optional dep; the token-id API works without it)"
            ) from exc
        self.path = path
        self._tok = AutoTokenizer.from_pretrained(path)

    def encode(self, text: str) -> list[int]:
        return list(self._tok(text).input_ids)

    def decode(self, token_ids: list[int]) -> str:
        # clean_up_tokenization_spaces rewrites EARLIER text when later
        # tokens arrive (' .' → '.'), which would break the streaming
        # invariant (concatenated deltas == final decode) — so cleanup
        # is off for BOTH this and the stream path, keeping decode
        # prefix-stable.
        return self._tok.decode(
            token_ids,
            skip_special_tokens=True,
            clean_up_tokenization_spaces=False,
        )

    @property
    def eos_id(self) -> int | None:
        return self._tok.eos_token_id

    def apply_chat_template(self, messages: list[dict]) -> list[int]:
        """Render an OpenAI-style ``messages`` list through the
        tokenizer's own chat template into prompt ids (the template
        ships in tokenizer_config.json next to imported weights).
        Raises ValueError when the tokenizer carries no template — a
        silently wrong fallback format would produce degraded output
        with no diagnostic."""
        if not getattr(self._tok, "chat_template", None):
            raise ValueError(
                "this tokenizer has no chat template; use "
                "/v1/completions with a raw prompt instead"
            )
        try:
            return list(
                self._tok.apply_chat_template(
                    messages, tokenize=True, add_generation_prompt=True
                )
            )
        except ValueError:
            raise
        except Exception as exc:
            # Real templates raise jinja2.TemplateError for unknown
            # roles / malformed content; surface it as the 400-mapped
            # ValueError, not a handler-killing 500.
            raise ValueError(
                f"chat template rendering failed: {exc}"
            ) from exc

    def stream_decoder(self) -> "StreamDecoder":
        return StreamDecoder(self)


class StreamDecoder:
    """Emit text deltas as tokens arrive; concatenated deltas (plus the
    final ``flush``) equal ``decode(all_tokens)`` exactly.

    Each push re-decodes the full sequence: O(total²) over a stream,
    but total is bounded by the engine's ``max_len`` and a Rust decode
    of even 8k ids is ~100 µs — the whole stream's decode overhead is
    milliseconds against minutes of generation, and anchored suffix
    decoding would reopen the sentencepiece leading-space bugs that
    plague chunked decoders.  Cleanup-off decode (see ``decode``) makes
    the full string prefix-stable; the guard below covers any exotic
    tokenizer that rewrites anyway (deltas pause, ``flush`` trues up).
    """

    def __init__(self, tokenizer: TextTokenizer):
        self._tokenizer = tokenizer
        self._tokens: list[int] = []
        self._emitted = ""
        self._prev_full = ""

    def push(self, token: int) -> str:
        """The new text this token completes ("" while mid-character)."""
        self._tokens.append(token)
        full = self._tokenizer.decode(self._tokens)
        # An incomplete multi-byte sequence decodes to U+FFFD at the
        # tail; hold those back until the next token completes them.
        # But only the NEWEST token's U+FFFDs are tentative: an
        # incomplete tail resolves by REWRITING its U+FFFD positions
        # (the completing bytes merge into one char), never by growing
        # PAST them — so a trailing U+FFFD the previous decode had is
        # confirmed real (byte-fallback on invalid bytes) once the text
        # strictly extends beyond it, and must stream, not stall until
        # flush.  Strictness matters: a growing incomplete prefix can
        # decode to the SAME single U+FFFD ('\xe2' and '\xe2\x88' both
        # → '�'), so an unchanged decode stays tentative.
        floor = (
            len(self._prev_full)
            if (
                len(full) > len(self._prev_full)
                and full.startswith(self._prev_full)
            )
            else len(self._emitted)
        )
        self._prev_full = full
        while full.endswith("�") and len(full) > floor:
            full = full[:-1]
        if not full.startswith(self._emitted):
            # Non-prefix-stable rewrite (shouldn't happen with cleanup
            # off): hold everything; flush() emits the authoritative
            # remainder.
            return ""
        delta = full[len(self._emitted):]
        self._emitted = full
        return delta

    def flush(self) -> str:
        """Anything still held back (sequence ended mid-character)."""
        full = self._tokenizer.decode(self._tokens)
        if not full.startswith(self._emitted):
            # Rewrite fallback — BEST-EFFORT: when a non-prefix-stable
            # rewrite occurred mid-stream, emitting from the divergence
            # point means the concatenated deltas may not exactly equal
            # decode(all_tokens) (the already-emitted prefix can't be
            # unsent); the final text is right from the divergence on.
            import os as _os

            common = _os.path.commonprefix([full, self._emitted])
            delta = full[len(common):]
        else:
            delta = full[len(self._emitted):]
        self._emitted = full
        return delta
