"""Serving self-registration: oim-serve announces itself to the registry.

The controller heartbeat pattern
(/root/reference/pkg/oim-controller/controller.go:425-468) applied to
the serving plane: a background thread re-``SetValue``s
``serve/<id>/address`` every ``delay`` seconds over a fresh
per-operation connection, so the key survives registry DB loss and the
instance survives registry restarts.  The router (serve/router.py)
discovers these keys by prefix query.

The CN contract is ``serve.<id>`` (registry authz allows exactly the
instance's own key — registry/registry.py ``SERVE_CN_PREFIX``).
"""

from __future__ import annotations

import threading

from oim_tpu import log
from oim_tpu.common import events, resilience


class ServeRegistration:
    """Background ``serve/<id>/address`` heartbeat.  ``start()`` returns
    self after the FIRST registration attempt (so a misconfigured
    address fails fast in the caller's face, not silently in a
    thread); subsequent re-registrations never raise."""

    def __init__(
        self,
        serve_id: str,
        registry_address: str,
        advertised_address: str,
        tls=None,
        delay: float = 60.0,
        retry=None,
        health=None,
        load=None,
        pool: str = "",
    ):
        if not serve_id or "/" in serve_id:
            raise ValueError(f"invalid serve id {serve_id!r}")
        self.serve_id = serve_id
        self.registry_address = registry_address
        self.advertised_address = advertised_address
        self.tls = tls
        self.delay = delay
        # Disaggregation pool role (oim-serve --pool): published beside
        # the address as leased ``serve/<id>/pool`` so pool membership
        # is registry-discoverable (the autoscaler's per-pool
        # watermarks and `oimctl top` read it without an HTTP hop; the
        # router reads the same role from /v1/info).  Empty = not
        # published (pre-disaggregation deployments stay byte-
        # identical on the wire).
        self.pool = pool
        # Optional load telemetry (callable → dict, the Engine.load()
        # shape): published each beat beside the address key as the
        # leased ``load/serve.<id>`` value — the autoscaler's
        # observation plane (oim_tpu/autoscale/load.py).  Mutable like
        # ``health``: serve_main assigns it once the engine exists.
        self.load = load
        self._load_publisher = None
        # Optional health gate (callable → bool), consulted each beat:
        # unhealthy → the key is actively WITHDRAWN (routers watching
        # ``serve/`` drop this instance at the DELETE event — faster
        # than unhealthy_after probe failures) and re-registration
        # pauses until health returns.  oim-serve wires this to "the
        # server has no latched error" (driver death, decode stall).
        # Mutable attribute: serve_main assigns it once the server
        # exists.
        self.health = health
        self._withdrawn = False
        # Shared bounded-retry policy (oim_tpu.common.resilience), capped
        # below the heartbeat period so ladders never overlap beats.
        if retry is None:
            retry = resilience.RetryPolicy.for_heartbeat(delay)
        self.retry = retry
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def register(self, retry=None) -> None:
        """One registration: fresh dial → SetValue → close.  The key is
        leased (3× the heartbeat delay): a crashed instance's address
        expires with a watch event instead of lingering.  Retried under
        the shared policy (or ``retry`` when given): a registry blip
        must not cost a whole beat of a 3-beat lease."""
        from oim_tpu.common.regdial import registry_channel
        from oim_tpu.spec import REGISTRY, oim_pb2

        policy = retry if retry is not None else self.retry

        def beat(attempt):
            # Per-attempt timeout shrinks to the remaining ladder budget
            # (a hanging registry must not stall the beat past it).
            timeout = attempt.clamped()
            ttl = max(1, int(self.delay * 3))
            with registry_channel(self.registry_address, self.tls) as channel:
                stub = REGISTRY.stub(channel)
                stub.SetValue(
                    oim_pb2.SetValueRequest(
                        value=oim_pb2.Value(
                            path=f"serve/{self.serve_id}/address",
                            value=self.advertised_address,
                        ),
                        ttl_seconds=ttl,
                    ),
                    timeout=timeout,
                )
                if self.pool:
                    # Same lease as the address: pool membership and
                    # reachability expire together.
                    stub.SetValue(
                        oim_pb2.SetValueRequest(
                            value=oim_pb2.Value(
                                path=f"serve/{self.serve_id}/pool",
                                value=self.pool,
                            ),
                            ttl_seconds=ttl,
                        ),
                        timeout=timeout,
                    )

        resilience.call_with_retry(
            beat, policy, component="oim-serve", op="Register"
        )
        self._publish_load()
        log.current().debug(
            "serve registered",
            id=self.serve_id,
            address=self.advertised_address,
        )

    def _publish_load(self) -> None:
        """Best-effort load beat after a successful registration: a
        missed one just ages the leased key toward its 3-beat expiry,
        so it must never fail the address heartbeat it rides on."""
        if self.load is None:
            return
        if self._load_publisher is None:
            from oim_tpu.autoscale.load import LoadPublisher

            self._load_publisher = LoadPublisher(
                f"serve.{self.serve_id}",
                self.registry_address,
                tls=self.tls,
                ttl_seconds=max(1.0, self.delay * 3),
            )
        try:
            self._load_publisher.publish(self.load())
        except Exception as exc:
            log.current().warning(
                "load publication failed", id=self.serve_id, error=str(exc)
            )

    def deregister(self) -> None:
        """Best-effort immediate removal of the discovery key (graceful
        drain): routers watching ``serve/`` stop sending new requests at
        the DELETE event rather than at lease expiry."""
        from oim_tpu.common.regdial import registry_channel
        from oim_tpu.spec import REGISTRY, oim_pb2

        try:
            with registry_channel(self.registry_address, self.tls) as channel:
                stub = REGISTRY.stub(channel)
                stub.SetValue(
                    oim_pb2.SetValueRequest(
                        value=oim_pb2.Value(
                            path=f"serve/{self.serve_id}/address", value=""
                        )
                    ),
                    timeout=5,
                )
                if self.pool:
                    stub.SetValue(
                        oim_pb2.SetValueRequest(
                            value=oim_pb2.Value(
                                path=f"serve/{self.serve_id}/pool",
                                value="",
                            )
                        ),
                        timeout=5,
                    )
            events.emit(
                "serve.deregister", component="oim-serve", subject=self.serve_id
            )
            if self._load_publisher is not None:
                # Drop the load key with the address: a withdrawn
                # instance must leave the fleet's utilization estimate
                # at the same watch event, not at lease expiry.
                self._load_publisher.withdraw()
        except Exception as exc:
            # The lease still expires the key; deregistration only
            # accelerates it.
            log.current().warning(
                "serve deregistration failed", error=str(exc)
            )

    def _loop(self) -> None:
        while not self._stop.wait(self.delay):
            try:
                if self.health is not None and not self.health():
                    if not self._withdrawn:
                        # One withdrawal per unhealthy episode; the
                        # lease would expire the key anyway, this gets
                        # routers off the instance in one watch event.
                        events.emit(
                            "serve.withdraw.unhealthy",
                            component="oim-serve",
                            severity=events.WARNING,
                            subject=self.serve_id,
                        )
                        log.current().warning(
                            "serve unhealthy; withdrawing registration",
                            id=self.serve_id,
                        )
                        self.deregister()
                        self._withdrawn = True
                    continue
                restored = self._withdrawn
                self._withdrawn = False
                self.register()
                if restored:
                    events.emit(
                        "serve.register",
                        component="oim-serve",
                        subject=self.serve_id,
                        address=self.advertised_address,
                        recovered=True,
                    )
            except Exception as exc:
                # Never let the heartbeat die: transient failures must
                # not permanently de-register the instance.
                events.emit(
                    "serve.register.failed",
                    component="oim-serve",
                    severity=events.WARNING,
                    subject=self.serve_id,
                    error=str(exc),
                )
                log.current().warning(
                    "serve registration failed",
                    registry=self.registry_address,
                    error=str(exc),
                )

    def start(self) -> "ServeRegistration":
        # Fail FAST on misconfiguration: one bounded attempt, no ladder —
        # a typo'd registry address should surface in seconds, not after
        # 80% of a 60s heartbeat period of retries.  The background loop
        # keeps the full beat-bounded policy for transient blips.
        self.register(retry=resilience.RetryPolicy.one_shot())
        # One timeline row per registration epoch (the first successful
        # beat), not one per heartbeat — churn shows as register /
        # deregister pairs, a flapping registry as register.failed rows.
        events.emit(
            "serve.register",
            component="oim-serve",
            subject=self.serve_id,
            address=self.advertised_address,
        )
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self, deregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if deregister:
            self.deregister()
