"""mTLS for the HTTP serving data plane.

≙ the reference's mTLS-everywhere stance (reference README.md:84-120:
every connection authenticates both ends against the deployment CA).
The gRPC control plane already lives by it (common/tlsconfig.py); this
module extends the same CA tree to the serving surface — the one
OUTWARD-facing API in the system, which previously had *less* protection
than any internal gRPC endpoint:

    client ⇄ oim-route ⇄ oim-serve      (HTTPS, client certs required)

Identity model: the deployment CA is PRIVATE and closed-world — holding
any CA-signed cert IS the authorization to speak to the data plane, the
same trust stance as the gRPC plane (where a cert's CN then scopes
WRITES; the serving API has no writes to scope).  Hostname checking is
disabled on purpose: components dial each other by registry-discovered
IP:port, and the cert's CN (``serve.<id>``, available to handlers via
``peer_common_name``) is the identity, not the network address —
exactly how the gRPC plane pins ``component.registry`` instead of a
hostname.

Servers wrap the LISTENING socket, so the TLS handshake happens on
accept in the serving threads; a client presenting no cert or a cert
from a different CA fails the handshake before a single byte of the
HTTP request is read.
"""

from __future__ import annotations

import ssl
import urllib.request
from http.server import ThreadingHTTPServer


def server_ssl_context(
    ca_file: str, cert_file: str, key_file: str,
    require_client_cert: bool = True,
) -> ssl.SSLContext:
    """TLS context for a serving listener: presents ``cert_file`` and
    (by default) REQUIRES a peer cert signed by ``ca_file`` — mTLS."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file)
    ctx.load_verify_locations(ca_file)
    if require_client_cert:
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_ssl_context(
    ca_file: str, cert_file: str | None = None, key_file: str | None = None
) -> ssl.SSLContext:
    """TLS context for dialing a serving endpoint: verifies the server
    chains to OUR CA (not the system roots), presents a client cert when
    given (required by mTLS servers).  See the module docstring for why
    ``check_hostname`` is off."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.load_verify_locations(ca_file)
    if cert_file:
        ctx.load_cert_chain(cert_file, key_file)
    return ctx


def opener(
    context: ssl.SSLContext | None,
) -> urllib.request.OpenerDirector:
    """urllib opener sending requests through ``context`` (plain HTTP
    opener when ``None`` — the no-TLS deployments keep working)."""
    if context is None:
        return urllib.request.build_opener()
    return urllib.request.build_opener(
        urllib.request.HTTPSHandler(context=context)
    )


# CN prefixes that carry a serving-plane identity.  Holding ANY
# deployment-CA cert opens the TLS handshake (closed-world CA, module
# docstring); this pin additionally requires the cert to BE a serving
# identity — a controller's controller.* or the registry's
# component.registry cert can no longer call the serving API or
# impersonate a backend to a router, matching the gRPC plane which pins
# CNs beyond the CA check
# (common/tlsconfig.py, registry CN authorization).
SERVING_CN_PREFIXES = ("serve.", "route.", "user.")


def authorize_serving_peer(handler) -> bool:
    """True when ``handler``'s peer may speak the serving data plane:
    plain HTTP (no identities to pin), or a TLS peer whose cert CN is a
    serving-plane identity (``serve.*`` backend, ``route.*`` router,
    ``user.*`` client).  Defense-in-depth over the CA gate."""
    getpeercert = getattr(handler.connection, "getpeercert", None)
    if getpeercert is None:
        return True
    cert = getpeercert()
    if not cert:
        # TLS without a client cert: the listener deliberately ran with
        # require_client_cert=False — nothing to pin.
        return True
    cn = _cert_common_name(cert)
    return cn is not None and cn.startswith(SERVING_CN_PREFIXES)


def check_serving_peer(handler) -> bool:
    """``authorize_serving_peer`` plus the 403 every serving handler
    sends on failure — call first in each do_GET/do_POST so both the
    router and backend reject non-serving identities identically."""
    import json

    if authorize_serving_peer(handler):
        return True
    body = json.dumps(
        {"error": "peer CN is not a serving-plane identity"}
    ).encode()
    handler.send_response(403)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)
    return False


def _cert_common_name(cert) -> str | None:
    for rdn in cert.get("subject", ()):
        for key, value in rdn:
            if key == "commonName":
                return value
    return None


def peer_common_name(handler) -> str | None:
    """CN of the authenticated client driving ``handler``'s request, or
    None on a plain-HTTP server (the gRPC plane's ``peer_common_name``
    contract, for HTTP handlers)."""
    getpeercert = getattr(handler.connection, "getpeercert", None)
    if getpeercert is None:
        return None
    cert = getpeercert()
    if not cert:
        return None
    return _cert_common_name(cert)


class TLSThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose accepted sockets speak TLS.

    The listener socket itself is wrapped with
    ``do_handshake_on_connect=False``, so the handshake happens lazily
    on the first I/O in the per-connection handler thread — a slow or
    hostile client cannot block the accept loop.  Handshake failures
    (wrong CA, no client cert) are an expected hostile-input event:
    counted per server instance, not tracebacked.  Anything that is NOT
    a TLS/connection-teardown error still goes through the default
    handler — a handler-side bug must stay loud.
    """

    def __init__(self, addr, handler_cls, ssl_context: ssl.SSLContext):
        super().__init__(addr, handler_cls)
        self.handshake_failures = 0
        self.socket = ssl_context.wrap_socket(
            self.socket, server_side=True, do_handshake_on_connect=False
        )

    def handle_error(self, request, client_address):
        import sys

        exc = sys.exc_info()[1]
        if isinstance(
            exc,
            (
                ssl.SSLError,
                ConnectionResetError,
                BrokenPipeError,
                ConnectionAbortedError,
                TimeoutError,
            ),
        ):
            # Failed handshakes / client teardown: the mTLS gate doing
            # its job, or a client hanging up — not a server bug.
            self.handshake_failures += 1
            return
        super().handle_error(request, client_address)
