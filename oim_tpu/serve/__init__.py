"""Serving engine: continuous batching over a slot-based KV cache.

New work for the TPU build (the reference is a storage control plane with
no inference surface; SURVEY.md §2.3's TPU-build column).  The engine is
the inference counterpart of ``cli/train_main.py``: it turns the decode
path (``models/decode.py``) into a multi-request server.
"""

from oim_tpu.serve.engine import (
    BlockAllocator,
    Engine,
    GenRequest,
    PagedCache,
    SlotCache,
)
from oim_tpu.serve.registration import ServeRegistration
from oim_tpu.serve.router import Router

__all__ = [
    "BlockAllocator",
    "Engine",
    "GenRequest",
    "PagedCache",
    "Router",
    "ServeRegistration",
    "SlotCache",
]
