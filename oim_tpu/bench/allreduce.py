"""ICI all-reduce bandwidth benchmark (BASELINE.md metric 2).

The proof workload for a CSI-provisioned slice: a ``psum`` all-reduce over
the ICI mesh, timed across buffer sizes, reported as perfdash ``PerfData``
(≙ the reference's perftype schema — the reference itself published no
numbers, SURVEY.md §6).

Bandwidth accounting follows the standard collective convention:

- **algbw** = per-chip buffer bytes / wall time — what the caller sees.
- **busbw** = algbw × 2(n−1)/n — the per-link traffic a ring/torus
  all-reduce actually moves (each element crosses every link twice,
  reduce-scatter + all-gather), which is the number to compare against the
  ICI line rate (the ≥90 % target).

XLA lowers ``psum`` to its torus-optimal all-reduce on TPU, so the
measured busbw *is* the ICI utilization; there is nothing to hand-tune at
this layer (How-to-Scale-Your-Model recipe: pick the mesh, let XLA place
the collective, measure).
"""

from __future__ import annotations

import statistics
import time

import numpy as np

from oim_tpu.perftype import PerfData

DEFAULT_SIZES_MB = (1, 4, 16, 64)


def _percentiles(samples_s: list[float]) -> dict[str, float]:
    ordered = sorted(samples_s)

    def pct(p: float) -> float:
        idx = min(len(ordered) - 1, int(round(p / 100 * (len(ordered) - 1))))
        return ordered[idx]

    return {
        "Perc50": pct(50) * 1e3,
        "Perc90": pct(90) * 1e3,
        "Perc99": pct(99) * 1e3,
        "Average": statistics.fmean(ordered) * 1e3,
    }


# Per-collective definitions: the shard-local op, the NCCL-convention
# bus-bandwidth factor (per-link traffic / algorithm bytes), and a
# correctness check on the result.  algbw denominator = per-chip shard
# bytes for all_reduce (the caller's buffer), total bytes for the
# resharding collectives (their "message" is the whole array).
COLLECTIVES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all")


def _collective_ops(jax, jnp, n: int, per_chip: int):
    def all_reduce(x):
        return jax.lax.psum(x, "x")

    def all_gather(x):
        return jax.lax.all_gather(x, "x", tiled=True)

    def reduce_scatter(x):
        return jax.lax.psum_scatter(x, "x", tiled=True)

    def all_to_all(x):
        return jax.lax.all_to_all(
            x.reshape(n, per_chip // n), "x", 0, 0, tiled=True
        ).reshape(-1)

    return {
        "all_reduce": (all_reduce, lambda a: a * 2 * (n - 1) / n),
        "all_gather": (all_gather, lambda a: a * (n - 1) / n),
        "reduce_scatter": (reduce_scatter, lambda a: a * (n - 1) / n),
        "all_to_all": (all_to_all, lambda a: a * (n - 1) / n),
    }


def _check(op: str, x, out, n: int, per_chip: int):
    """The timed collective must actually be the collective."""
    xf = np.asarray(x, dtype=np.float32).reshape(n, per_chip)
    got = np.asarray(out, dtype=np.float32)
    if op == "all_reduce":
        np.testing.assert_allclose(got[:per_chip], xf.sum(0), rtol=2e-2)
    elif op == "all_gather":
        # out_specs=P(None): the replicated global result IS the full
        # gathered array.
        np.testing.assert_allclose(got, xf.reshape(-1), rtol=2e-2)
    elif op == "reduce_scatter":
        np.testing.assert_allclose(got, xf.sum(0), rtol=2e-2)
    elif op == "all_to_all":
        want = (
            xf.reshape(n, n, per_chip // n)
            .transpose(1, 0, 2)
            .reshape(-1)
        )
        np.testing.assert_allclose(got, want, rtol=2e-2)


def collective_bench(
    devices=None,
    sizes_mb=DEFAULT_SIZES_MB,
    dtype: str = "bfloat16",
    iters: int = 10,
    warmup: int = 3,
    line_rate_gbps: float = 0.0,
    ops=("all_reduce",),
) -> PerfData:
    """Time XLA collectives over a 1-D mesh and report GB/s/chip.

    Runs on any backend: the 8-virtual-device CPU mesh validates the
    plumbing and each collective's correctness; on a TPU slice the same
    code measures real ICI.  ``line_rate_gbps`` (per-direction ICI link
    rate) adds a ``BusBwFraction`` bucket for the ≥90 % target.
    ``ops`` ⊆ COLLECTIVES selects the matrix (all-reduce is the headline;
    all-gather/reduce-scatter are its halves; all-to-all is the Ulysses
    sequence-parallel primitive).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("x",))
    jdtype = jnp.dtype(dtype)
    unknown = set(ops) - set(COLLECTIVES)
    if unknown:
        raise ValueError(f"unknown collectives {sorted(unknown)}")

    perf = PerfData(
        labels={
            "benchmark": "ici-collectives",
            "devices": str(n),
            "dtype": dtype,
            "backend": devices[0].platform,
        }
    )
    for size_mb in sizes_mb:
        base_per_chip = int(size_mb * 2**20 // jdtype.itemsize)
        sharding = NamedSharding(mesh, P("x"))
        for op in ops:
            # all_to_all splits the shard by n; round ITS buffer only so
            # the other collectives' sizeMB label stays exact.
            per_chip = base_per_chip
            if op == "all_to_all":
                per_chip -= per_chip % max(n, 1)
            x = jax.device_put(
                jnp.arange(per_chip * n, dtype=jnp.float32).astype(jdtype),
                sharding,
            )
            fn, bus_factor = _collective_ops(jax, jnp, n, per_chip)[op]
            # Each (size, op) point benchmarks a DIFFERENT program — a
            # fresh jit per iteration is the measurement, not a leak,
            # and the warmup loop below pays its compile before timing.
            step = jax.jit(  # oimlint: disable=retrace-risk
                jax.shard_map(
                    fn, mesh=mesh, in_specs=P("x"),
                    out_specs=P(None) if op == "all_gather" else P("x"),
                    check_vma=False,
                )
            )
            out = step(x)
            _check(op, x, out, n, per_chip)
            for _ in range(warmup):
                step(x).block_until_ready()
            samples = []
            for _ in range(iters):
                t0 = time.perf_counter()
                step(x).block_until_ready()
                samples.append(time.perf_counter() - t0)
            latency = _percentiles(samples)
            best = min(samples)
            shard_bytes = per_chip * jdtype.itemsize
            # NCCL convention: the "message" is the per-rank buffer for
            # all_reduce/reduce_scatter/all_to_all (each chip's input is
            # one shard) and the total array for all_gather (whose output
            # is n shards) — anything else inflates busbw past the line
            # rate, which would mask the underperforming links the >=90%
            # target exists to catch.
            msg_bytes = (
                shard_bytes * n if op == "all_gather" else shard_bytes
            )
            algbw = msg_bytes / best / 1e9
            busbw = bus_factor(algbw) if n > 1 else algbw
            buckets = {
                **latency,
                "AlgBwGBps": algbw,
                "BusBwGBps": busbw,
            }
            if line_rate_gbps > 0:
                buckets["BusBwFraction"] = busbw / line_rate_gbps
            perf.add(
                unit="ms",
                labels={
                    "sizeMB": str(size_mb),
                    "collective": op,
                    "metricOf": "latency+bandwidth",
                },
                **buckets,
            )
    return perf


def allreduce_bench(*args, **kwargs) -> PerfData:
    """The headline metric (BASELINE.md): ``collective_bench`` restricted
    to all-reduce."""
    return collective_bench(*args, ops=("all_reduce",), **kwargs)
