"""ICI all-reduce bandwidth benchmark (BASELINE.md metric 2).

The proof workload for a CSI-provisioned slice: a ``psum`` all-reduce over
the ICI mesh, timed across buffer sizes, reported as perfdash ``PerfData``
(≙ the reference's perftype schema — the reference itself published no
numbers, SURVEY.md §6).

Bandwidth accounting follows the standard collective convention:

- **algbw** = per-chip buffer bytes / wall time — what the caller sees.
- **busbw** = algbw × 2(n−1)/n — the per-link traffic a ring/torus
  all-reduce actually moves (each element crosses every link twice,
  reduce-scatter + all-gather), which is the number to compare against the
  ICI line rate (the ≥90 % target).

XLA lowers ``psum`` to its torus-optimal all-reduce on TPU, so the
measured busbw *is* the ICI utilization; there is nothing to hand-tune at
this layer (How-to-Scale-Your-Model recipe: pick the mesh, let XLA place
the collective, measure).
"""

from __future__ import annotations

import statistics
import time

import numpy as np

from oim_tpu.perftype import PerfData

DEFAULT_SIZES_MB = (1, 4, 16, 64)


def _percentiles(samples_s: list[float]) -> dict[str, float]:
    ordered = sorted(samples_s)

    def pct(p: float) -> float:
        idx = min(len(ordered) - 1, int(round(p / 100 * (len(ordered) - 1))))
        return ordered[idx]

    return {
        "Perc50": pct(50) * 1e3,
        "Perc90": pct(90) * 1e3,
        "Perc99": pct(99) * 1e3,
        "Average": statistics.fmean(ordered) * 1e3,
    }


def allreduce_bench(
    devices=None,
    sizes_mb=DEFAULT_SIZES_MB,
    dtype: str = "bfloat16",
    iters: int = 10,
    warmup: int = 3,
    line_rate_gbps: float = 0.0,
) -> PerfData:
    """Time ``psum`` over a 1-D mesh of ``devices`` and report GB/s/chip.

    Runs on any backend: the 8-virtual-device CPU mesh validates the
    plumbing and the collective's correctness; on a TPU slice the same
    code measures real ICI.  ``line_rate_gbps`` (per-direction ICI link
    rate) adds a ``BusBwFraction`` bucket for the ≥90 % target.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("x",))
    jdtype = jnp.dtype(dtype)

    def _reduce(x):
        return jax.lax.psum(x, "x")

    reduce_step = jax.jit(
        jax.shard_map(
            _reduce, mesh=mesh, in_specs=P("x"), out_specs=P("x"), check_vma=False
        )
    )

    perf = PerfData(
        labels={
            "benchmark": "ici-all-reduce",
            "devices": str(n),
            "dtype": dtype,
            "backend": devices[0].platform,
        }
    )
    for size_mb in sizes_mb:
        per_chip = int(size_mb * 2**20 // jdtype.itemsize)
        sharding = NamedSharding(mesh, P("x"))
        x = jax.device_put(
            jnp.arange(per_chip * n, dtype=jnp.float32).astype(jdtype),
            sharding,
        )
        # Correctness first (the collective must actually reduce): compare
        # one shard against the expected sum of n identical shards... each
        # shard differs, so check the global invariant on a small slice.
        reduced = reduce_step(x)
        expected = np.asarray(
            jnp.sum(
                np.asarray(x, dtype=np.float32).reshape(n, per_chip), axis=0
            ),
            dtype=np.float32,
        )
        got = np.asarray(reduced, dtype=np.float32)[:per_chip]
        np.testing.assert_allclose(got, expected, rtol=2e-2)

        for _ in range(warmup):
            reduce_step(x).block_until_ready()
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            reduce_step(x).block_until_ready()
            samples.append(time.perf_counter() - t0)
        latency = _percentiles(samples)
        best = min(samples)
        bytes_per_chip = per_chip * jdtype.itemsize
        algbw = bytes_per_chip / best / 1e9
        busbw = algbw * (2 * (n - 1) / n) if n > 1 else algbw
        buckets = {
            **latency,
            "AlgBwGBps": algbw,
            "BusBwGBps": busbw,
        }
        if line_rate_gbps > 0:
            buckets["BusBwFraction"] = busbw / line_rate_gbps
        perf.add(
            unit="ms",
            labels={"sizeMB": str(size_mb), "metricOf": "latency+bandwidth"},
            **buckets,
        )
    return perf
