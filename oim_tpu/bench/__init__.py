"""Benchmark harnesses emitting perfdash-style results (oim_tpu.perftype)."""

from oim_tpu.bench.allreduce import COLLECTIVES, allreduce_bench, collective_bench

__all__ = ["COLLECTIVES", "allreduce_bench", "collective_bench"]
