"""Benchmark harnesses emitting perfdash-style results (oim_tpu.perftype)."""

from oim_tpu.bench.allreduce import allreduce_bench

__all__ = ["allreduce_bench"]
