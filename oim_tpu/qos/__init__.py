"""Multi-tenant QoS: tenant tiers, quotas, fair share, preemption.

The policy layer that turns tenant identity (mTLS peer CN, PR 9) and
the host-RAM park/swap substrate (PR 15) into actual isolation:

- :mod:`oim_tpu.qos.policy` — the declarative tenant-policy model
  (tiers, weighted shares, token quotas, rate limits, preemption
  priority), its tolerant decode, and the ``qos/tenants`` registry key;
- :mod:`oim_tpu.qos.publish` — read/write that key as the operator.

Enforcement lives where the resources live: the router (rate limits +
token quotas → 429/Retry-After), the engine's admission wave (weighted
fair share + priority preemption via slot parking), and the KV tiers
(premium prefixes pin against demotion).  See doc/serving.md
"Multi-tenant QoS".
"""

from oim_tpu.qos.policy import (  # noqa: F401
    QOS_TENANTS_KEY,
    TIERS,
    QosPolicy,
    TenantPolicy,
    decode_policy,
    encode_policy,
    load_policy_file,
)
