"""Declarative tenant-policy model for multi-tenant QoS.

One policy document answers every "may this tenant ..." question the
serving plane asks:

- **tier** — ``premium`` / ``standard`` / ``best_effort``.  The tier
  carries the defaults for everything below, plus the two tier-global
  behaviors: preemption priority (a higher-priority tenant's admission
  may park a strictly-lower-priority tenant's slot) and prefix pinning
  (premium prefix-cache entries never demote to the host tier while a
  lower tier's entry can go instead).
- **weight** — the tenant's share of engine admission under
  contention.  The engine's fair-share scheduler is virtual-time
  (stride) based: each admission advances the tenant's vtime by
  ``tokens / weight``, and the queue head with the LEAST vtime admits
  next — so over time token throughput converges to the weight ratio
  regardless of who queues faster.
- **rate_rps / tokens_per_s** (+ bursts) — router-side token buckets.
  Exceeding either sheds the request at the door with 429 and a
  per-tenant Retry-After (the PR 6 shed taxonomy, new reason
  ``quota``) — cheap rejection before any accelerator state is touched.

The document lives in the registry under :data:`QOS_TENANTS_KEY`
(operator-published, see :mod:`oim_tpu.qos.publish`) with a static-file
fallback for registry-less deployments.  Decode is TOLERANT the same
way ``autoscale/load.decode_load`` is: unknown fields are ignored,
wrong-typed fields fall back to defaults, and a torn/foreign value
yields the all-defaults policy — a bad publish degrades to "no QoS",
never to a crashed data plane.

Identity fallback (the satellite-2 bugfix): requests with no mTLS peer
CN all collapse into the ``"anon"`` tenant.  Without a policy that is
one shared identity consuming every tier's headroom, so anon gets an
EXPLICIT default tier (``anon_tier``, best-effort) distinct from the
default for unknown-but-authenticated CNs (``default_tier``,
standard).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

# Registry key the operator publishes the policy document under
# (authz: registry/authz.py grants it to user.admin explicitly).
QOS_TENANTS_KEY = "qos/tenants"

ANON_TENANT = "anon"

# Tier order is privilege order (most to least).  ``best_effort`` is
# spelled with an underscore everywhere (metric label values, JSON) —
# decode normalizes "best-effort" for operator convenience.
TIERS = ("premium", "standard", "best_effort")

# Tier defaults: admission weight (fair-share stride denominators) and
# preemption priority (an admission may park only a STRICTLY lower
# priority victim — equal tiers never preempt each other, so a
# policy-less fleet behaves exactly as before this PR).
TIER_WEIGHT = {"premium": 8.0, "standard": 4.0, "best_effort": 1.0}
TIER_PRIORITY = {"premium": 2, "standard": 1, "best_effort": 0}


def _normalize_tier(value, default: str) -> str:
    if not isinstance(value, str):
        return default
    tier = value.strip().lower().replace("-", "_")
    return tier if tier in TIERS else default


@dataclass(frozen=True)
class TenantPolicy:
    """One tenant's resolved policy (defaults already applied)."""

    tenant: str
    tier: str = "standard"
    # 0 means "tier default" for every numeric knob; rate/quota knobs
    # additionally mean "unlimited" when the tier default is also 0
    # (the built-in tiers impose no caps — caps are per-tenant policy).
    weight: float = 0.0
    rate_rps: float = 0.0  # request-rate bucket refill (0 = unlimited)
    rate_burst: float = 0.0  # bucket depth (0 → max(1, rate_rps))
    tokens_per_s: float = 0.0  # token-quota bucket refill (0 = unlimited)
    token_burst: float = 0.0  # bucket depth (0 → 16 × tokens_per_s)

    @property
    def effective_weight(self) -> float:
        if self.weight > 0:
            return self.weight
        return TIER_WEIGHT.get(self.tier, 1.0)

    @property
    def priority(self) -> int:
        return TIER_PRIORITY.get(self.tier, 0)

    @property
    def pin_prefix(self) -> bool:
        """Premium prefix-cache entries pin against host-tier demotion
        and eviction while any lower-tier victim exists."""
        return self.tier == "premium"

    @property
    def effective_rate_burst(self) -> float:
        if self.rate_burst > 0:
            return self.rate_burst
        return max(1.0, self.rate_rps)

    @property
    def effective_token_burst(self) -> float:
        if self.token_burst > 0:
            return self.token_burst
        return 16.0 * self.tokens_per_s


_TENANT_FIELDS = (
    ("weight", 0.0),
    ("rate_rps", 0.0),
    ("rate_burst", 0.0),
    ("tokens_per_s", 0.0),
    ("token_burst", 0.0),
)


@dataclass(frozen=True)
class QosPolicy:
    """The whole fleet's tenant policy: per-tenant rows + the two
    fallback tiers.  Immutable — engines/routers swap the reference
    atomically on policy reload."""

    tenants: dict = field(default_factory=dict)  # tenant → TenantPolicy
    default_tier: str = "standard"  # unknown but authenticated CNs
    anon_tier: str = "best_effort"  # the no-mTLS identity sink

    def lookup(self, tenant: str) -> TenantPolicy:
        """The resolved policy for ``tenant`` — synthesizes a
        tier-default row for tenants with no explicit entry, so callers
        never branch on presence."""
        name = tenant or ANON_TENANT
        entry = self.tenants.get(name)
        if entry is not None:
            return entry
        tier = self.anon_tier if name == ANON_TENANT else self.default_tier
        return TenantPolicy(tenant=name, tier=tier)

    def tier_of(self, tenant: str) -> str:
        return self.lookup(tenant).tier


#: The policy a fleet runs with when nothing was published: every
#: authenticated tenant standard, anon best-effort, no caps — fair
#: share is a no-op between equal weights and nothing throttles.
DEFAULT_POLICY = QosPolicy()


def decode_policy(text) -> QosPolicy:
    """Tolerant decode of a policy document (JSON text or bytes).

    Never raises: a torn, foreign or wrong-shaped value yields
    :data:`DEFAULT_POLICY`; per-field damage falls back per field.  The
    mirror of ``autoscale/load.decode_load`` — schema skew between
    fleet generations must degrade, not crash.
    """
    if isinstance(text, bytes):
        try:
            text = text.decode()
        except UnicodeDecodeError:
            return DEFAULT_POLICY
    if not text or not isinstance(text, str):
        return DEFAULT_POLICY
    try:
        doc = json.loads(text)
    except ValueError:
        return DEFAULT_POLICY
    if not isinstance(doc, dict):
        return DEFAULT_POLICY
    default_tier = _normalize_tier(doc.get("default_tier"), "standard")
    anon_tier = _normalize_tier(doc.get("anon_tier"), "best_effort")
    tenants: dict[str, TenantPolicy] = {}
    rows = doc.get("tenants")
    if isinstance(rows, dict):
        for name, row in rows.items():
            if not isinstance(name, str) or not name:
                continue
            if not isinstance(row, dict):
                row = {}
            kwargs = {}
            for key, default in _TENANT_FIELDS:
                value = row.get(key, default)
                # int is acceptable where float is expected (JSON
                # writers emit 5, not 5.0) — the decode_load leniency.
                if isinstance(value, bool) or not isinstance(
                    value, (int, float)
                ):
                    value = default
                kwargs[key] = max(0.0, float(value))
            tenants[name] = TenantPolicy(
                tenant=name,
                tier=_normalize_tier(row.get("tier"), default_tier),
                **kwargs,
            )
    return QosPolicy(
        tenants=tenants, default_tier=default_tier, anon_tier=anon_tier
    )


def encode_policy(policy: QosPolicy) -> str:
    """The inverse of :func:`decode_policy` — what
    ``oim_tpu.qos.publish`` writes under ``qos/tenants``."""
    return json.dumps({
        "default_tier": policy.default_tier,
        "anon_tier": policy.anon_tier,
        "tenants": {
            name: {
                "tier": row.tier,
                "weight": row.weight,
                "rate_rps": row.rate_rps,
                "rate_burst": row.rate_burst,
                "tokens_per_s": row.tokens_per_s,
                "token_burst": row.token_burst,
            }
            for name, row in sorted(policy.tenants.items())
        },
    }, sort_keys=True)


def load_policy_file(path: str) -> QosPolicy:
    """Static-file fallback for registry-less deployments (the
    ``--qos-policy`` flag).  Missing/unreadable file → defaults, same
    degrade-don't-crash stance as the registry path."""
    try:
        with open(path, encoding="utf-8") as fh:
            return decode_policy(fh.read())
    except OSError:
        return DEFAULT_POLICY
