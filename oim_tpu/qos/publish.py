"""Read/write the ``qos/tenants`` registry key.

The policy document is operator-owned: only ``user.admin`` may write
it (registry/authz.py carries an explicit grant so the QoS key is
visible policy, not an accident of the admin wildcard).  Every serving
component READS it — reads are unrestricted on the registry plane —
and decodes tolerantly, so a half-rolled-out schema change degrades to
defaults instead of taking the data plane down.
"""

from __future__ import annotations

from oim_tpu.qos.policy import QOS_TENANTS_KEY, QosPolicy, decode_policy


def fetch_policy(channel, timeout: float = 10.0) -> QosPolicy:
    """The currently-published policy, or the all-defaults policy when
    the key is absent/torn.  ``channel`` is an open registry gRPC
    channel (``common.regdial.registry_channel``)."""
    from oim_tpu.spec import REGISTRY, oim_pb2

    reply = REGISTRY.stub(channel).GetValues(
        oim_pb2.GetValuesRequest(path=QOS_TENANTS_KEY), timeout=timeout
    )
    for value in reply.values:
        if value.path == QOS_TENANTS_KEY and value.value:
            return decode_policy(value.value)
    return decode_policy("")


def publish_policy(channel, text: str, timeout: float = 10.0) -> None:
    """Write the policy document (already-encoded JSON text; callers
    validate with ``decode_policy`` first if they care).  Runs as the
    operator identity — the mTLS client cert on ``channel`` must be
    ``user.admin``."""
    from oim_tpu.spec import REGISTRY, oim_pb2

    REGISTRY.stub(channel).SetValue(
        oim_pb2.SetValueRequest(
            value=oim_pb2.Value(path=QOS_TENANTS_KEY, value=text)
        ),
        timeout=timeout,
    )
