"""Structured, leveled, context-carried logging.

Capability mirror of the reference's ``pkg/log`` (reference
pkg/log/log.go:37-191): a small logger interface whose instances travel with
the execution context so nested calls inherit per-request tags (e.g. the gRPC
method), with swappable implementations (plain-text, test-capturing, null).

The idiomatic Python translation of Go's ``context.Context`` carriage is a
``contextvars.ContextVar``: ``with_logger()``/``with_fields()`` are context
managers instead of ``WithLogger(ctx)`` returning a new ctx, and ``current()``
replaces ``FromContext(ctx)``.
"""

from __future__ import annotations

import contextlib
import contextvars
import sys
import threading
import time
from typing import Any, Iterator

from oim_tpu.log.level import Level, threshold_from_string

__all__ = [
    "Logger",
    "SimpleLogger",
    "TestLogger",
    "NullLogger",
    "Record",
    "L",
    "set_global",
    "current",
    "with_logger",
    "with_fields",
    "Level",
]


class Logger:
    """Base logger: level methods layered over one ``output`` primitive.

    Mirrors ``LoggerBase`` embedding 15 convenience methods over 3 primitives
    (reference pkg/log/helper.go:16-37); here every level method funnels into
    ``output(level, msg, fields)`` and ``with_fields`` returns a child bound
    to extra key/values (≙ ``Logger.With``, reference pkg/log/log.go:83-110).
    """

    def __init__(self, fields: dict[str, Any] | None = None) -> None:
        self.fields: dict[str, Any] = dict(fields or {})

    # -- primitive, implemented by subclasses ------------------------------
    def output(self, level: Level, msg: str, fields: dict[str, Any]) -> None:
        raise NotImplementedError

    def child(self, fields: dict[str, Any]) -> "Logger":
        """Construct the same kind of logger with merged bound fields."""
        raise NotImplementedError

    # -- convenience surface ----------------------------------------------
    def with_fields(self, **kv: Any) -> "Logger":
        merged = dict(self.fields)
        merged.update(kv)
        return self.child(merged)

    def _log(self, level: Level, msg: str, kv: dict[str, Any]) -> None:
        fields = dict(self.fields)
        fields.update(kv)
        self.output(level, msg, fields)

    def debug(self, msg: str, **kv: Any) -> None:
        self._log(Level.DEBUG, msg, kv)

    def info(self, msg: str, **kv: Any) -> None:
        self._log(Level.INFO, msg, kv)

    def warning(self, msg: str, **kv: Any) -> None:
        self._log(Level.WARNING, msg, kv)

    def error(self, msg: str, **kv: Any) -> None:
        self._log(Level.ERROR, msg, kv)

    def fatal(self, msg: str, **kv: Any) -> None:
        self._log(Level.FATAL, msg, kv)
        raise SystemExit(msg)


def _format_fields(fields: dict[str, Any]) -> str:
    if not fields:
        return ""
    return " " + " ".join(f"{k}={fields[k]!r}" for k in sorted(fields))


class SimpleLogger(Logger):
    """Plain-text threshold-filtered logger (≙ simpleLogger, simple.go:26-131)."""

    def __init__(
        self,
        threshold: Level = Level.INFO,
        out=None,
        fields: dict[str, Any] | None = None,
        timestamps: bool = True,
    ) -> None:
        super().__init__(fields)
        self.threshold = threshold
        self.out = out if out is not None else sys.stderr
        self.timestamps = timestamps
        self._lock = threading.Lock()

    def child(self, fields: dict[str, Any]) -> "SimpleLogger":
        c = SimpleLogger(self.threshold, self.out, fields, self.timestamps)
        c._lock = self._lock
        return c

    def output(self, level: Level, msg: str, fields: dict[str, Any]) -> None:
        if level < self.threshold:
            return
        ts = (
            time.strftime("%Y-%m-%d %H:%M:%S ", time.localtime())
            if self.timestamps
            else ""
        )
        line = f"{ts}{level.name[0]} {msg}{_format_fields(fields)}\n"
        with self._lock:
            try:
                self.out.write(line)
            except (ValueError, OSError):
                # Stream closed/broken under us (interpreter teardown,
                # pytest capture ending, a consumer pipe exiting) —
                # logging must never crash the thread that called it.
                pass


class Record:
    __slots__ = ("level", "msg", "fields")

    def __init__(self, level: Level, msg: str, fields: dict[str, Any]):
        self.level, self.msg, self.fields = level, msg, fields

    def __repr__(self) -> str:
        return f"Record({self.level.name}, {self.msg!r}, {self.fields!r})"


class TestLogger(Logger):
    """Captures records for assertions (≙ testlog, testlog/testlog.go:9-20)."""

    def __init__(self, fields: dict[str, Any] | None = None, parent=None) -> None:
        super().__init__(fields)
        self.records: list[Record] = [] if parent is None else parent.records

    def child(self, fields: dict[str, Any]) -> "TestLogger":
        return TestLogger(fields, parent=self)

    def output(self, level: Level, msg: str, fields: dict[str, Any]) -> None:
        self.records.append(Record(level, msg, fields))

    def messages(self) -> list[str]:
        return [r.msg for r in self.records]


class NullLogger(Logger):
    def child(self, fields: dict[str, Any]) -> "NullLogger":
        return NullLogger(fields)

    def output(self, level: Level, msg: str, fields: dict[str, Any]) -> None:
        pass


_global = SimpleLogger()
_ctx: contextvars.ContextVar[Logger | None] = contextvars.ContextVar(
    "oim_tpu_logger", default=None
)


def set_global(logger: Logger) -> None:
    """≙ ``log.Set`` (reference pkg/log/log.go:120-130)."""
    global _global
    _global = logger


def L() -> Logger:
    """The global logger (≙ ``log.L()``)."""
    return _global


def current() -> Logger:
    """The context logger, falling back to the global one (≙ ``FromContext``)."""
    return _ctx.get() or _global


@contextlib.contextmanager
def with_logger(logger: Logger) -> Iterator[Logger]:
    """Run a block with ``logger`` as the context logger (≙ ``WithLogger``)."""
    token = _ctx.set(logger)
    try:
        yield logger
    finally:
        _ctx.reset(token)


@contextlib.contextmanager
def with_fields(**kv: Any) -> Iterator[Logger]:
    """Bind fields onto the context logger for a block (≙ ``log.With``)."""
    child = current().with_fields(**kv)
    token = _ctx.set(child)
    try:
        yield child
    finally:
        _ctx.reset(token)


def init_from_string(spec: str) -> None:
    """Configure the global logger threshold from a ``-log.level`` style string
    (≙ ``InitSimpleFlags``, reference pkg/log/simple.go:30-41)."""
    set_global(SimpleLogger(threshold=threshold_from_string(spec)))
