"""Log levels (≙ reference pkg/log/level/level.go:1-70)."""

from __future__ import annotations

import enum


class Level(enum.IntEnum):
    DEBUG = 10
    INFO = 20
    WARNING = 30
    ERROR = 40
    FATAL = 50


_NAMES = {l.name.lower(): l for l in Level}
_NAMES["warn"] = Level.WARNING


def threshold_from_string(s: str) -> Level:
    try:
        return _NAMES[s.strip().lower()]
    except KeyError:
        raise ValueError(
            f"invalid log level {s!r}; one of {sorted(_NAMES)}"
        ) from None
