"""Chaos-injection harness for the control AND serve planes.

Three fault surfaces, one seeded-RNG discipline (tests replay exactly):

- ``FlakyChannel``: wraps a ``grpc.Channel`` and injects transport
  failures into unary calls — *before* the call (``error``: the request
  never reached the peer), *after* it (``disconnect``: executed, reply
  lost — the ambiguous window idempotency keys exist for, named to match
  the fake agent's ``chaos_disconnect``), or around it (``delay``).
  Exercises client-side retry/breaker logic against a live in-process
  server without touching the server.
- ``FlakyAgent``: arms the fake tpu-agent's ``chaos_*`` ``inject_fault``
  knobs (oim_tpu/agent/fake.py) for a scope — whole-stack chaos at the
  device-plane hop, where drops surface to the CSI plane as UNAVAILABLE
  through the controller.
- ``FlakyHTTPBackend``: an HTTP proxy in front of a real ``oim-serve``
  backend that kills responses mid-stream (the backend-process-death
  signature the router's stream-splice failover exists for), truncates
  buffered bodies short of their declared Content-Length, flakes its
  ``/healthz``, and slow-walks chunks — the serve-plane soak surface.

All are product-adjacent test infrastructure (importable from tests and
from `oimctl`-driven game days), not production code paths: nothing in
the daemons imports this module.
"""

from __future__ import annotations

import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

import grpc

from oim_tpu.agent import Agent


class InjectedRpcError(grpc.RpcError):
    """A synthetic RpcError carrying a chosen status (``code=None``
    reproduces the locally-raised-error shape whose formatting crash the
    status classifier guards against)."""

    def __init__(
        self,
        code: grpc.StatusCode | None = grpc.StatusCode.UNAVAILABLE,
        details: str = "injected fault",
    ):
        super().__init__(details)
        self._code = code
        self._details = details

    def code(self):
        return self._code

    def details(self):
        return self._details


class _FlakyMulticallable:
    def __init__(self, channel: "FlakyChannel", inner, path: str):
        self._channel = channel
        self._inner = inner
        self._path = path

    def __call__(self, request, **kwargs):
        hit = self._channel._roll(self._path)
        if hit:
            mode = self._channel.mode
            if mode == "error":
                raise InjectedRpcError(self._channel.code)
            if mode == "delay":
                time.sleep(self._channel.delay_s)
            elif mode not in ("disconnect", "none_code"):
                raise ValueError(f"unknown chaos mode {mode!r}")
            if mode == "none_code":
                raise InjectedRpcError(None, "locally raised injected fault")
        reply = self._inner(request, **kwargs)
        if hit and self._channel.mode == "disconnect":
            # Executed server-side; the reply is eaten.
            raise InjectedRpcError(
                grpc.StatusCode.UNAVAILABLE, "injected reply drop"
            )
        return reply


class FlakyChannel:
    """grpc.Channel wrapper injecting faults into unary calls.

    ``mode``: ``error`` (fail before the peer sees it, status ``code``),
    ``disconnect`` (execute, then eat the reply as UNAVAILABLE — the
    executed-but-reply-lost window, same word as the fake agent's
    ``chaos_disconnect``), ``delay`` (sleep ``delay_s`` first),
    ``none_code`` (raise an RpcError whose ``code()`` is None — the
    local-error regression shape).

    ``rate`` + ``seed`` pick victims reproducibly; ``fail_next(n)``
    overrides the dice for exactly the next ``n`` calls (deterministic
    unit-test scripting).  Streaming calls pass through unwrapped.
    """

    def __init__(
        self,
        inner: grpc.Channel,
        mode: str = "error",
        rate: float = 1.0,
        seed: int = 0,
        code: grpc.StatusCode = grpc.StatusCode.UNAVAILABLE,
        delay_s: float = 0.05,
    ):
        self._inner = inner
        self.mode = mode
        self.rate = rate
        self.code = code
        self.delay_s = delay_s
        self._rng = random.Random(seed)
        self._forced = 0
        self.calls = 0
        self.injected = 0

    def fail_next(self, n: int = 1) -> None:
        self._forced += n

    def _roll(self, _path: str) -> bool:
        self.calls += 1
        if self._forced > 0:
            self._forced -= 1
            self.injected += 1
            return True
        if self._rng.random() < self.rate:
            self.injected += 1
            return True
        return False

    def unary_unary(self, path, **kwargs):
        return _FlakyMulticallable(
            self, self._inner.unary_unary(path, **kwargs), path
        )

    def unary_stream(self, path, **kwargs):
        return self._inner.unary_stream(path, **kwargs)

    def stream_stream(self, path, **kwargs):
        return self._inner.stream_stream(path, **kwargs)

    def subscribe(self, callback, try_to_connect=False):
        return self._inner.subscribe(callback, try_to_connect)

    def unsubscribe(self, callback):
        return self._inner.unsubscribe(callback)

    def close(self):
        return self._inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FlakyHTTPBackend:
    """Serve-plane chaos: an HTTP proxy in front of a real oim-serve
    instance.

    Faults (seeded like ``FlakyChannel``; ``fail_next(n)`` scripts the
    next ``n`` POSTs deterministically):

    - ``kill_rate``: probability a proxied POST's response is severed
      mid-body.  Close-delimited NDJSON streams are cut after
      ``kill_after_lines`` COMPLETE lines (clean FIN, no terminal
      done/error line — exactly what a killed backend process looks
      like to the router); Content-Length bodies are cut at half their
      declared length (truncation proof).
    - ``healthz_error_rate``: probability a GET /healthz answers an
      injected 503 — the health-flapping surface.
    - ``delay_s``: sleep per response chunk (slow backend).
    - ``fail_next_get(n, path_prefix)``: scripts the next ``n`` GETs
      whose path starts with ``path_prefix`` to be severed mid-body —
      the kill-mid-slot-ship surface (ISSUE 17): a ``GET /v1/slot``
      export cut at half its declared Content-Length is exactly what
      a source dying mid-migration looks like to the router's
      ``ship_slot`` (short read → fall back to splice recompute).

    PUT and DELETE forward transparently (the migration wire's ingest
    and release verbs), POST-kill-eligible like POSTs are.

    ``start()`` returns self; point the router at ``.url``.
    """

    def __init__(
        self,
        backend_url: str,
        host: str = "127.0.0.1",
        port: int = 0,
        kill_rate: float = 0.0,
        kill_after_lines: int = 1,
        healthz_error_rate: float = 0.0,
        delay_s: float = 0.0,
        seed: int = 0,
    ):
        self.backend_url = backend_url.rstrip("/")
        self.kill_rate = kill_rate
        self.kill_after_lines = kill_after_lines
        self.healthz_error_rate = healthz_error_rate
        self.delay_s = delay_s
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._forced = 0
        self._forced_get = 0
        self._forced_get_prefix = ""
        self.requests = 0
        self.kills = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, *args):
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/healthz" and outer._roll(
                    outer.healthz_error_rate
                ):
                    body = b'{"ok": false, "error": "injected"}'
                    self.send_response(503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                outer._forward(self, None, kill=outer._get_kill(path))

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                outer._forward(self, self.rfile.read(length))

            def do_PUT(self):
                length = int(self.headers.get("Content-Length", "0"))
                outer._forward(
                    self, self.rfile.read(length), method="PUT"
                )

            def do_DELETE(self):
                outer._forward(self, None, method="DELETE")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def fail_next(self, n: int = 1) -> None:
        with self._lock:
            self._forced += n

    def fail_next_get(self, n: int = 1, path_prefix: str = "/v1/slot") -> None:
        """Script the next ``n`` matching GETs to be severed mid-body
        (kill-mid-slot-ship, ISSUE 17).  GETs are otherwise never
        kill-eligible — health probes and info fetches must stay
        honest while the scripted ship kill lands deterministically."""
        with self._lock:
            self._forced_get += n
            self._forced_get_prefix = path_prefix

    def _get_kill(self, path: str) -> bool:
        with self._lock:
            if (
                self._forced_get > 0
                and path.startswith(self._forced_get_prefix)
            ):
                self._forced_get -= 1
                self.requests += 1
                return True
            return False

    def _roll(self, rate: float) -> bool:
        with self._lock:
            return self._rng.random() < rate

    def _kill_roll(self) -> bool:
        """Decide whether THIS POST should be killed.  ``kills`` is
        counted at execution (_count_kill), not here — a roll whose
        response turns out to be an HTTP error, or a stream shorter
        than ``kill_after_lines``, injects nothing, and the soak
        assertions must count real injections only."""
        with self._lock:
            self.requests += 1
            if self._forced > 0:
                self._forced -= 1
                return True
            return self._rng.random() < self.kill_rate

    def _count_kill(self) -> None:
        with self._lock:
            self.kills += 1

    def _forward(
        self, handler, body: bytes | None, kill: bool = False,
        method: str | None = None,
    ) -> None:
        """Proxy one request; POST/PUTs are kill-eligible by roll,
        GETs only by ``fail_next_get`` scripting (the ``kill``
        argument)."""
        kill = kill or (body is not None and self._kill_roll())
        headers = (
            {"Content-Type": "application/json"} if body is not None
            else {}
        )
        # A transparent proxy must not strip the observability/deadline
        # headers: the splice-trace tests assert both failover attempts
        # share the router span's trace id THROUGH this proxy.
        for name in ("traceparent", "x-oim-deadline-ms"):
            if handler.headers.get(name):
                headers[name] = handler.headers[name]
        req = urllib.request.Request(
            self.backend_url + handler.path, data=body, headers=headers,
            method=method,
        )
        try:
            resp = urllib.request.urlopen(req, timeout=600)
        except urllib.error.HTTPError as exc:
            payload = exc.read()
            handler.send_response(exc.code)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Content-Length", str(len(payload)))
            retry_after = exc.headers.get("Retry-After")
            if retry_after:
                handler.send_header("Retry-After", retry_after)
            handler.end_headers()
            handler.wfile.write(payload)
            return
        except (urllib.error.URLError, OSError):
            handler.connection.close()  # look as dead as the backend
            return
        with resp:
            clen = resp.headers.get("Content-Length")
            handler.send_response(resp.status)
            handler.send_header(
                "Content-Type",
                resp.headers.get("Content-Type", "application/json"),
            )
            if clen is not None:
                # Declared even when killing: a short body under a
                # declared length is the truncation proof the router's
                # buffered-resubmit path keys on.
                handler.send_header("Content-Length", clen)
            if resp.headers.get("traceparent"):
                handler.send_header(
                    "traceparent", resp.headers["traceparent"]
                )
            handler.end_headers()
            if clen is not None:
                data = resp.read()
                if kill:
                    self._count_kill()
                    handler.wfile.write(data[: len(data) // 2])
                    handler.wfile.flush()
                    handler.connection.close()
                    return
                handler.wfile.write(data)
                return
            # Close-delimited stream: forward COMPLETE lines only, so a
            # kill always lands between lines (a real process death can
            # land mid-line; the router discards partial lines either
            # way, this just makes soak token counts deterministic).
            lines = 0
            buf = b""
            while True:
                if self.delay_s:
                    time.sleep(self.delay_s)
                chunk = resp.read(256)
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    handler.wfile.write(line + b"\n")
                    handler.wfile.flush()
                    lines += 1
                    if kill and lines >= self.kill_after_lines:
                        self._count_kill()
                        handler.connection.close()
                        return
            if buf:
                handler.wfile.write(buf)

    def start(self) -> "FlakyHTTPBackend":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=10)


class FlakyAgent:
    """Scoped ``chaos_*`` arming of a fake tpu-agent.

    >>> with FlakyAgent(sock, "chaos_disconnect", rate=0.2, seed=7):
    ...     soak()  # 20% of device-plane requests lose their reply
    ...             # (after executing), severing the connection
    """

    def __init__(
        self,
        socket_path: str,
        kind: str,
        rate: float = 1.0,
        seed: int | None = 0,
        delay_s: float | None = None,
        error_code: int | None = None,
        methods: list[str] | None = None,
        connect: Callable[[str], Agent] = Agent,
    ):
        self.socket_path = socket_path
        self.kind = kind
        self.rate = rate
        self.seed = seed
        self.delay_s = delay_s
        self.error_code = error_code
        self.methods = methods
        self._connect = connect

    def arm(self) -> None:
        with self._connect(self.socket_path) as agent:
            agent.inject_chaos(
                self.kind,
                rate=self.rate,
                seed=self.seed,
                delay_s=self.delay_s,
                error_code=self.error_code,
                methods=self.methods,
            )

    def heal(self) -> None:
        with self._connect(self.socket_path) as agent:
            agent.inject_chaos("chaos_clear")

    def __enter__(self) -> "FlakyAgent":
        self.arm()
        return self

    def __exit__(self, *exc) -> None:
        self.heal()
