"""Chaos-injection harness for the control plane.

Two fault surfaces, one seeded-RNG discipline (tests replay exactly):

- ``FlakyChannel``: wraps a ``grpc.Channel`` and injects transport
  failures into unary calls — *before* the call (``error``: the request
  never reached the peer), *after* it (``disconnect``: executed, reply
  lost — the ambiguous window idempotency keys exist for, named to match
  the fake agent's ``chaos_disconnect``), or around it (``delay``).
  Exercises client-side retry/breaker logic against a live in-process
  server without touching the server.
- ``FlakyAgent``: arms the fake tpu-agent's ``chaos_*`` ``inject_fault``
  knobs (oim_tpu/agent/fake.py) for a scope — whole-stack chaos at the
  device-plane hop, where drops surface to the CSI plane as UNAVAILABLE
  through the controller.

Both are product-adjacent test infrastructure (importable from tests and
from `oimctl`-driven game days), not production code paths: nothing in
the daemons imports this module.
"""

from __future__ import annotations

import random
import time
from typing import Callable

import grpc

from oim_tpu.agent import Agent


class InjectedRpcError(grpc.RpcError):
    """A synthetic RpcError carrying a chosen status (``code=None``
    reproduces the locally-raised-error shape whose formatting crash the
    status classifier guards against)."""

    def __init__(
        self,
        code: grpc.StatusCode | None = grpc.StatusCode.UNAVAILABLE,
        details: str = "injected fault",
    ):
        super().__init__(details)
        self._code = code
        self._details = details

    def code(self):
        return self._code

    def details(self):
        return self._details


class _FlakyMulticallable:
    def __init__(self, channel: "FlakyChannel", inner, path: str):
        self._channel = channel
        self._inner = inner
        self._path = path

    def __call__(self, request, **kwargs):
        hit = self._channel._roll(self._path)
        if hit:
            mode = self._channel.mode
            if mode == "error":
                raise InjectedRpcError(self._channel.code)
            if mode == "delay":
                time.sleep(self._channel.delay_s)
            elif mode not in ("disconnect", "none_code"):
                raise ValueError(f"unknown chaos mode {mode!r}")
            if mode == "none_code":
                raise InjectedRpcError(None, "locally raised injected fault")
        reply = self._inner(request, **kwargs)
        if hit and self._channel.mode == "disconnect":
            # Executed server-side; the reply is eaten.
            raise InjectedRpcError(
                grpc.StatusCode.UNAVAILABLE, "injected reply drop"
            )
        return reply


class FlakyChannel:
    """grpc.Channel wrapper injecting faults into unary calls.

    ``mode``: ``error`` (fail before the peer sees it, status ``code``),
    ``disconnect`` (execute, then eat the reply as UNAVAILABLE — the
    executed-but-reply-lost window, same word as the fake agent's
    ``chaos_disconnect``), ``delay`` (sleep ``delay_s`` first),
    ``none_code`` (raise an RpcError whose ``code()`` is None — the
    local-error regression shape).

    ``rate`` + ``seed`` pick victims reproducibly; ``fail_next(n)``
    overrides the dice for exactly the next ``n`` calls (deterministic
    unit-test scripting).  Streaming calls pass through unwrapped.
    """

    def __init__(
        self,
        inner: grpc.Channel,
        mode: str = "error",
        rate: float = 1.0,
        seed: int = 0,
        code: grpc.StatusCode = grpc.StatusCode.UNAVAILABLE,
        delay_s: float = 0.05,
    ):
        self._inner = inner
        self.mode = mode
        self.rate = rate
        self.code = code
        self.delay_s = delay_s
        self._rng = random.Random(seed)
        self._forced = 0
        self.calls = 0
        self.injected = 0

    def fail_next(self, n: int = 1) -> None:
        self._forced += n

    def _roll(self, _path: str) -> bool:
        self.calls += 1
        if self._forced > 0:
            self._forced -= 1
            self.injected += 1
            return True
        if self._rng.random() < self.rate:
            self.injected += 1
            return True
        return False

    def unary_unary(self, path, **kwargs):
        return _FlakyMulticallable(
            self, self._inner.unary_unary(path, **kwargs), path
        )

    def unary_stream(self, path, **kwargs):
        return self._inner.unary_stream(path, **kwargs)

    def stream_stream(self, path, **kwargs):
        return self._inner.stream_stream(path, **kwargs)

    def subscribe(self, callback, try_to_connect=False):
        return self._inner.subscribe(callback, try_to_connect)

    def unsubscribe(self, callback):
        return self._inner.unsubscribe(callback)

    def close(self):
        return self._inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FlakyAgent:
    """Scoped ``chaos_*`` arming of a fake tpu-agent.

    >>> with FlakyAgent(sock, "chaos_disconnect", rate=0.2, seed=7):
    ...     soak()  # 20% of device-plane requests lose their reply
    ...             # (after executing), severing the connection
    """

    def __init__(
        self,
        socket_path: str,
        kind: str,
        rate: float = 1.0,
        seed: int | None = 0,
        delay_s: float | None = None,
        error_code: int | None = None,
        methods: list[str] | None = None,
        connect: Callable[[str], Agent] = Agent,
    ):
        self.socket_path = socket_path
        self.kind = kind
        self.rate = rate
        self.seed = seed
        self.delay_s = delay_s
        self.error_code = error_code
        self.methods = methods
        self._connect = connect

    def arm(self) -> None:
        with self._connect(self.socket_path) as agent:
            agent.inject_chaos(
                self.kind,
                rate=self.rate,
                seed=self.seed,
                delay_s=self.delay_s,
                error_code=self.error_code,
                methods=self.methods,
            )

    def heal(self) -> None:
        with self._connect(self.socket_path) as agent:
            agent.inject_chaos("chaos_clear")

    def __enter__(self) -> "FlakyAgent":
        self.arm()
        return self

    def __exit__(self, *exc) -> None:
        self.heal()
