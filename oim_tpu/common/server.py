"""Non-blocking gRPC server wrapper.

≙ reference pkg/oim-common/server.go:43-137 (``NonBlockingGRPCServer``):
start/wait/stop/force-stop lifecycle around a grpc server bound to a parsed
``unix://``/``tcp://`` endpoint, with ``addr()`` reporting the actual bound
address so tests can listen on ``tcp://127.0.0.1:0`` and discover the port.
"""

from __future__ import annotations

import os
from concurrent import futures
from typing import Callable

import grpc

from oim_tpu.common import endpoint as ep
from oim_tpu.common.tlsconfig import TLSConfig
from oim_tpu import log

Registrar = Callable[[grpc.Server], None]


def _unix_socket_alive(path: str) -> bool:
    import socket as _socket

    s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    try:
        s.settimeout(0.5)
        s.connect(path)
        return True
    except OSError:
        return False
    finally:
        s.close()


class NonBlockingGRPCServer:
    def __init__(
        self,
        endpoint: str,
        tls: TLSConfig | None = None,
        interceptors: tuple = (),
        max_workers: int = 16,
        options: tuple = (),
    ) -> None:
        self.endpoint = ep.parse(endpoint)
        self.tls = tls
        self.interceptors = interceptors
        self.max_workers = max_workers
        self.options = options
        self._server: grpc.Server | None = None
        self._port: int | None = None

    def start(self, *registrars: Registrar) -> None:
        if self._server is not None:
            raise RuntimeError("server already started")
        if self.endpoint.is_unix:
            sock = self.endpoint.address
            parent = os.path.dirname(sock)
            if parent:
                os.makedirs(parent, exist_ok=True)
            if os.path.exists(sock):
                # Only remove the socket if nothing is serving on it; silently
                # unlinking a live server's socket would steal the address.
                if _unix_socket_alive(sock):
                    raise RuntimeError(f"{self.endpoint} is already in use")
                os.unlink(sock)
        server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self.max_workers),
            interceptors=list(self.interceptors),
            # Tolerate client keepalive pings on idle long-lived streams
            # (WatchValues/etcd Watch clients ping every 30 s — see
            # regdial.KEEPALIVE_OPTIONS); without this the server GOAWAYs
            # them with ENHANCE_YOUR_CALM after two "unnecessary" pings.
            options=[
                ("grpc.http2.min_ping_interval_without_data_ms", 20_000),
                ("grpc.keepalive_permit_without_calls", 1),
            ]
            + list(self.options),
        )
        for registrar in registrars:
            registrar(server)
        listen = self.endpoint.grpc_listen()
        if self.tls is not None:
            port = server.add_secure_port(listen, self.tls.server_credentials())
        else:
            port = server.add_insecure_port(listen)
        if port == 0:
            raise RuntimeError(f"failed to bind {self.endpoint}")
        self._port = port
        self._server = server
        server.start()
        log.current().info("gRPC server listening", endpoint=str(self.addr()))

    def addr(self) -> ep.Endpoint:
        """Actual bound endpoint (resolves ``:0`` to the real port)."""
        if self._server is None or self._port is None:
            raise RuntimeError("server not started")
        if self.endpoint.is_unix:
            return self.endpoint
        host = self.endpoint.address.rsplit(":", 1)[0]
        return ep.Endpoint(self.endpoint.scheme, f"{host}:{self._port}")

    def wait(self) -> None:
        assert self._server is not None
        self._server.wait_for_termination()

    def run(self, *registrars: Registrar) -> None:
        """start() + wait(), the blocking mode used by the CLI binaries
        (≙ reference server.go:131-137)."""
        self.start(*registrars)
        self.wait()

    def stop(self, grace: float | None = 5.0) -> None:
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None

    def force_stop(self) -> None:
        self.stop(grace=None)
