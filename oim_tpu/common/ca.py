"""Certificate-authority utility for the mTLS control mesh.

The reference drives its CN-based authorization model from a shell script
(`test/setup-ca.sh`, invoked at reference test/test.make:188-191) producing a
CA plus per-component certs named ``component.registry``, ``controller.<id>``,
``host.<id>``, ``user.admin``.  Here the same capability is library code (used
by tests, the demo cluster and deploy bootstrap) built on ``cryptography``.

Naming convention (≙ reference README.md:84-120):
  component.registry   the registry server
  controller.<id>      a controller (may SetValue only its own address)
  host.<id>            a CSI node agent (may proxy only to controller.<id>)
  user.admin           operator; may SetValue anything
"""

from __future__ import annotations

import datetime
import os
from dataclasses import dataclass

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID


@dataclass
class Credential:
    cert_pem: bytes
    key_pem: bytes


def _key() -> ec.EllipticCurvePrivateKey:
    return ec.generate_private_key(ec.SECP256R1())


def _key_pem(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


class CertAuthority:
    """An in-memory CA that issues component certificates."""

    def __init__(self, name: str = "OIM TPU CA") -> None:
        self.name = name
        self._key = _key()
        subject = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, name)])
        now = datetime.datetime.now(datetime.timezone.utc)
        self._cert = (
            x509.CertificateBuilder()
            .subject_name(subject)
            .issuer_name(subject)
            .public_key(self._key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=3650))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None), True)
            .sign(self._key, hashes.SHA256())
        )

    @property
    def ca_pem(self) -> bytes:
        return self._cert.public_bytes(serialization.Encoding.PEM)

    def issue(
        self,
        common_name: str,
        dns_names: tuple[str, ...] = (),
        ip_addresses: tuple[str, ...] = ("127.0.0.1",),
    ) -> Credential:
        """Issue a cert whose CN and SAN carry ``common_name``.

        The SAN always includes the CN itself as a DNS name so clients can pin
        the peer via TLS server-name override (the reference pins ServerName to
        the expected CN, pkg/oim-common/grpc.go:77-101); localhost + loopback
        are included for tests and same-host deployments.
        """
        import ipaddress

        key = _key()
        now = datetime.datetime.now(datetime.timezone.utc)
        sans: list[x509.GeneralName] = [x509.DNSName(common_name)]
        sans += [x509.DNSName(d) for d in dns_names if d != common_name]
        if "localhost" not in (common_name, *dns_names):
            sans.append(x509.DNSName("localhost"))
        sans += [x509.IPAddress(ipaddress.ip_address(i)) for i in ip_addresses]
        cert = (
            x509.CertificateBuilder()
            .subject_name(
                x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
            )
            .issuer_name(self._cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(x509.SubjectAlternativeName(sans), False)
            .add_extension(x509.BasicConstraints(ca=False, path_length=None), True)
            .sign(self._key, hashes.SHA256())
        )
        return Credential(
            cert_pem=cert.public_bytes(serialization.Encoding.PEM),
            key_pem=_key_pem(key),
        )

    def write_tree(self, directory: str, names: list[str]) -> None:
        """Write ``ca.crt`` plus ``<name>.crt``/``<name>.key`` per component,
        the on-disk layout the reference's setup-ca.sh produces in ``_work/ca``."""
        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, "ca.crt"), "wb") as f:
            f.write(self.ca_pem)
        for name in names:
            cred = self.issue(name)
            with open(os.path.join(directory, f"{name}.crt"), "wb") as f:
                f.write(cred.cert_pem)
            with open(os.path.join(directory, f"{name}.key"), "wb") as f:
                f.write(cred.key_pem)
