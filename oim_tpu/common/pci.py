"""PCI BDF parsing / merging / pretty-printing.

Capability mirror of reference pkg/oim-common/pci.go:19-91: BDF strings in the
form ``[[domain:]bus:]device.function`` with hex components; any component may
be "unknown", encoded as 0xFFFF (no real component can reach it — domain is 16
bits in sysfs but 0xFFFF is reserved here, like the reference). TPU chips show
up under the same sysfs PCI namespace (/dev/accelN ↔ 0000:xx:00.0), so the
type is reused unchanged; ``merge`` implements the registry-default completion
trick (``CompletePCIAddress``, reference pkg/oim-csi-driver/remote.go:170-190).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

UNKNOWN = 0xFFFF

_BDF_RE = re.compile(
    r"^(?:(?:(?P<domain>[0-9a-fA-F]{1,4}):)?(?P<bus>[0-9a-fA-F]{1,4}):)?"
    r"(?P<device>[0-9a-fA-F]{1,4})\.(?P<function>[0-9a-fA-F]{1,4})$"
)


@dataclass(frozen=True)
class PCIAddress:
    domain: int = UNKNOWN
    bus: int = UNKNOWN
    device: int = UNKNOWN
    function: int = UNKNOWN

    def __str__(self) -> str:
        def c(v: int, width: int) -> str:
            return "*" * width if v == UNKNOWN else f"{v:0{width}x}"

        return (
            f"{c(self.domain, 4)}:{c(self.bus, 2)}:"
            f"{c(self.device, 2)}.{c(self.function, 1)}"
        )

    def complete(self) -> bool:
        return UNKNOWN not in (self.domain, self.bus, self.device, self.function)


def parse_bdf_string(s: str) -> PCIAddress:
    """Parse ``[[domain:]bus:]device.function``; missing parts are UNKNOWN.

    ≙ ``ParseBDFString`` (reference pkg/oim-common/pci.go:19-58).
    """
    m = _BDF_RE.match(s.strip())
    if not m:
        raise ValueError(f"invalid PCI BDF {s!r}")

    def g(name: str, width: int) -> int:
        v = m.group(name)
        if v is None:
            return UNKNOWN
        value = int(v, 16)
        # Range-check explicit components before the UNKNOWN encoding kicks
        # in: an explicit "ffff" bus/device/function is a typo, not a
        # wildcard.  Only the 16-bit domain reserves 0xFFFF as unknown.
        if value >= (1 << width) or (width < 16 and value == UNKNOWN):
            raise ValueError(f"PCI BDF component {name}={v!r} out of range in {s!r}")
        return value

    return PCIAddress(
        g("domain", 16), g("bus", 8), g("device", 8), g("function", 8)
    )


def merge(primary: PCIAddress, fallback: PCIAddress) -> PCIAddress:
    """Fill UNKNOWN components of ``primary`` from ``fallback``.

    ≙ the registry-default merging in ``CompletePCIAddress`` (reference
    pkg/oim-csi-driver/remote.go:170-190): the controller reply may carry a
    partial address that the registry's ``<id>/pci`` default completes.
    """

    def pick(a: int, b: int) -> int:
        return b if a == UNKNOWN else a

    return PCIAddress(
        pick(primary.domain, fallback.domain),
        pick(primary.bus, fallback.bus),
        pick(primary.device, fallback.device),
        pick(primary.function, fallback.function),
    )
