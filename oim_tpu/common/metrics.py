"""Process metrics with Prometheus text exposition.

The reference has **no** metrics: the only perf artifact it ships is the
perfdash ``perftype`` schema vendored for the k8s e2e framework
(reference test/e2e/perftype/perftype.go:26-53, and SURVEY.md §5 records
"No Prometheus metrics in OIM").  This module supplies what operators of
the reference had to live without, dependency-free: counters, gauges and
histograms with labels, a per-process registry, a gRPC server
interceptor recording per-method call counts and latencies, and a tiny
stdlib HTTP endpoint serving the standard ``/metrics`` text format
(Prometheus exposition format 0.0.4) that any scraper understands.

Design notes:
- Metric instruments are cheap under concurrency: one lock per metric,
  plain dict of label-tuple → float.  No background threads.
- Label values are escaped per the exposition format (backslash, quote,
  newline).
- The HTTP server is optional and per-daemon (``--metrics-endpoint``);
  embedders can instead call ``render()`` and publish however they like.
"""

from __future__ import annotations

import contextlib
import http.server
import threading
import time
from typing import Callable, Iterable

import grpc

from oim_tpu.common.interceptors import ObservingServerInterceptor

# ---------------------------------------------------------------------------
# Instruments


def _escape(value: str) -> str:
    return (
        value.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _labels_key(
    names: tuple[str, ...], values: tuple[str, ...]
) -> tuple[str, ...]:
    if len(values) != len(names):
        raise ValueError(f"expected labels {names}, got {values}")
    return values


def _render_labels(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing, optionally labelled."""

    kind = "counter"

    def __init__(self, name: str, help_: str, labels: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = labels
        self._lock = threading.Lock()
        self._values: dict[tuple[str, ...], float] = {}

    def inc(self, *label_values: str, by: float = 1.0) -> None:
        key = _labels_key(self.label_names, label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, *label_values: str) -> float:
        with self._lock:
            return self._values.get(label_values, 0.0)

    def render(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._values.items())
        for values, count in items:
            yield (
                f"{self.name}{_render_labels(self.label_names, values)}"
                f" {_format_float(count)}"
            )


class Gauge:
    """Set/add-style instantaneous value."""

    kind = "gauge"

    def __init__(self, name: str, help_: str, labels: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = labels
        self._lock = threading.Lock()
        self._values: dict[tuple[str, ...], float] = {}
        self._callbacks: dict[tuple[str, ...], Callable[[], float]] = {}

    def set(self, value: float, *label_values: str) -> None:
        key = _labels_key(self.label_names, label_values)
        with self._lock:
            self._values[key] = float(value)

    def add(self, delta: float, *label_values: str) -> None:
        key = _labels_key(self.label_names, label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta

    def set_function(
        self, fn: Callable[[], float], *label_values: str
    ) -> None:
        """Lazily evaluated at scrape time (e.g. 'chips free' asks the
        allocator rather than mirroring it)."""
        key = _labels_key(self.label_names, label_values)
        with self._lock:
            self._callbacks[key] = fn

    def value(self, *label_values: str) -> float:
        with self._lock:
            cb = self._callbacks.get(label_values)
        if cb is not None:
            return float(cb())
        with self._lock:
            return self._values.get(label_values, 0.0)

    def remove(self, *label_values: str, fn: Callable | None = None) -> None:
        """Drop a series (a closed component deregisters itself).  With
        ``fn``, remove only if that exact callback is still installed —
        a newer instance that took over the series is left alone."""
        key = _labels_key(self.label_names, label_values)
        with self._lock:
            if fn is not None and self._callbacks.get(key) is not fn:
                return
            self._callbacks.pop(key, None)
            self._values.pop(key, None)

    def render(self) -> Iterable[str]:
        with self._lock:
            items = dict(self._values)
            callbacks = dict(self._callbacks)
        for key, cb in callbacks.items():
            try:
                items[key] = float(cb())
            except Exception:
                continue  # a failing callback must not break the scrape
        for values, v in sorted(items.items()):
            yield (
                f"{self.name}{_render_labels(self.label_names, values)}"
                f" {_format_float(v)}"
            )


# Latency buckets suited to a control plane: 1ms .. 60s.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0, 60.0,
)

# Sub-millisecond buckets for the data plane and per-token latencies:
# DEFAULT_BUCKETS' 1ms floor lumps everything faster into one bucket,
# which hides exactly the distributions that matter on a TPU host
# (batch assembly, prefetch waits, per-token decode are all tens to
# hundreds of microseconds when healthy).  50µs .. 10s.
FAST_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 1.0, 2.5, 10.0,
)


class Histogram:
    """Cumulative-bucket histogram (the Prometheus shape)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str,
        labels: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.help = help_
        self.label_names = labels
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        # label values → (per-bucket counts, total count, sum)
        self._series: dict[tuple[str, ...], list] = {}

    def observe(self, value: float, *label_values: str) -> None:
        key = _labels_key(self.label_names, label_values)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = [[0] * len(self.buckets), 0, 0.0]
                self._series[key] = series
            counts, _, _ = series
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
            series[1] += 1
            series[2] += value

    def count(self, *label_values: str) -> int:
        with self._lock:
            series = self._series.get(label_values)
            return series[1] if series else 0

    def render(self) -> Iterable[str]:
        with self._lock:
            items = sorted(
                (k, (list(v[0]), v[1], v[2])) for k, v in self._series.items()
            )
        for values, (counts, total, sum_) in items:
            for bound, count in zip(self.buckets, counts):
                labels = _render_labels(
                    self.label_names + ("le",),
                    values + (_format_float(bound),),
                )
                yield f"{self.name}_bucket{labels} {count}"
            inf_labels = _render_labels(
                self.label_names + ("le",), values + ("+Inf",)
            )
            yield f"{self.name}_bucket{inf_labels} {total}"
            plain = _render_labels(self.label_names, values)
            yield f"{self.name}_sum{plain} {_format_float(sum_)}"
            yield f"{self.name}_count{plain} {total}"


def _format_float(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


# ---------------------------------------------------------------------------
# Registry


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def register(self, metric):
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing  # idempotent by name (shared instruments)
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name, help_, labels=()):
        return self.register(Counter(name, help_, labels))

    def gauge(self, name, help_, labels=()):
        return self.register(Gauge(name, help_, labels))

    def histogram(self, name, help_, labels=(), buckets=DEFAULT_BUCKETS):
        return self.register(Histogram(name, help_, labels, buckets))

    def render(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for metric in metrics:
            lines.append(f"# HELP {metric.name} {metric.help}")
            lines.append(f"# TYPE {metric.name} {metric.kind}")
            lines.extend(metric.render())
        return "\n".join(lines) + "\n"


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry


# ---------------------------------------------------------------------------
# Shared resilience instruments (used by oim_tpu.common.resilience): defined
# here, on the process registry, so every daemon that touches the retry
# layer exports identical series names — the incident-time queries in
# doc/operations.md depend on these exact shapes.

RPC_ATTEMPTS = _registry.counter(
    "oim_rpc_attempts_total",
    "Client-side RPC attempts through the shared retry layer, by outcome "
    "(ok / retryable / fatal).",
    ("component", "op", "outcome"),
)
RPC_RETRIES = _registry.counter(
    "oim_rpc_retries_total",
    "Re-attempts issued after a retryable failure.",
    ("component", "op"),
)
RPC_LATENCY = _registry.histogram(
    "oim_rpc_latency_seconds",
    "Whole-operation client latency through the retry layer (all attempts "
    "plus backoff sleeps).",
    ("component", "op"),
)
BREAKER_TRANSITIONS = _registry.counter(
    "oim_breaker_transitions_total",
    "Circuit-breaker state transitions, by target and entered state.",
    ("target", "state"),
)

# ---------------------------------------------------------------------------
# Serving-pipeline instruments (used by oim_tpu.serve.engine): the decode
# pipeline's health triad, defined here like the resilience set so the
# doc/operations.md "Serving pipeline tuning" queries see identical series
# names from every engine in the fleet.  Per-engine label: several engines
# can share one process (tests, multi-model hosts).

SERVE_PIPELINE_DEPTH = _registry.gauge(
    "oim_serve_pipeline_depth",
    "Configured decode pipeline depth: 1 = serial dispatch-then-readback, "
    "2 = dispatch-ahead double buffering (chunk N+1 dispatched before "
    "chunk N's readback).",
    ("engine",),
)
SERVE_DEVICE_IDLE = _registry.counter(
    "oim_serve_device_idle_seconds_total",
    "Estimated accelerator idle wall time: gaps between a completed "
    "readback and the next dispatch with nothing queued on the device.  "
    "Grows steadily on a serial engine; near-flat when the pipeline "
    "keeps the device fed.",
    ("engine",),
)
SERVE_OVERLAP_RATIO = _registry.gauge(
    "oim_serve_overlap_ratio",
    "Fraction of decode-readback wall time spent while another chunk was "
    "already dispatched (readback the device computed through).  0 on a "
    "serial engine; approaches 1 when the pipeline is winning.",
    ("engine",),
)

# ---------------------------------------------------------------------------
# Serve-plane fault-tolerance instruments (engine sheds/deadlines, the
# driver-side stall watchdog, and the router's stream-splice failover):
# shared definitions like the pipeline triad above, so the incident
# queries in doc/operations.md "Serving failure modes" see one series
# shape across the fleet.

SERVE_STALLS = _registry.counter(
    "oim_serve_stalls_total",
    "Decode stalls detected by the driver-side watchdog: a dispatched "
    "chunk exceeded a multiple of its EWMA wall time (device hang / XLA "
    "wedge).  Each one failed the in-flight requests fast and flipped "
    "/healthz unhealthy.",
    ("engine",),
)
SERVE_SHED = _registry.counter(
    "oim_serve_shed_total",
    "Requests shed (or clamped) by overload protection, by reason: "
    "queue_full = admission queue at capacity (HTTP 429), deadline = "
    "request deadline expired before it touched a slot, brownout = "
    "max_tokens clamped under sustained queue pressure (served, not "
    "rejected), quota = router-side per-tenant rate/token bucket "
    "exhausted (HTTP 429 with a per-tenant Retry-After; the tenant "
    "breakdown lives in oim_serve_qos_total).",
    ("reason",),
)
SERVE_FAILOVERS = _registry.counter(
    "oim_serve_failovers_total",
    "Router failovers after a backend died mid-request, by outcome: "
    "spliced = the remainder of an in-flight stream was re-decoded on "
    "another backend and spliced into the same client stream, "
    "resubmitted = a buffered (non-stream) request re-ran whole on "
    "another backend, gave_up = no healthy backend could finish it.",
    ("outcome",),
)
SERVE_DEADLINE_EXPIRED = _registry.counter(
    "oim_serve_deadline_expired_total",
    "Requests failed because their deadline expired — shed from the "
    "admission queue or reaped mid-decode (slot freed at the next "
    "pipeline boundary).",
)

# ---------------------------------------------------------------------------
# Disaggregated prefill/decode instruments (serve/disagg.py): the
# router's KV-ship health.  Shared definitions like the fault-tolerance
# set so the doc/operations.md incident queries see one series shape.

SERVE_KV_SHIP_SECONDS = _registry.histogram(
    "oim_serve_kv_ship_seconds",
    "Wall time of one KV ship (GET /v1/kv off the prefill backend + "
    "PUT /v1/kv into the decode backend), observed by the router.  "
    "Growing tails here eat the TTFT win disaggregation exists for — "
    "compare against oim_serve_prefill_seconds before raising the "
    "prompt threshold.",
)
SERVE_KV_SHIP_BYTES = _registry.counter(
    "oim_serve_kv_ship_bytes_total",
    "Bytes of KV block payload shipped between pools (manifest + raw "
    "leaves), router-observed — the disaggregation path's network "
    "cost.",
)
SERVE_DISAGG = _registry.counter(
    "oim_serve_disagg_requests_total",
    "Disaggregated generate requests by outcome: shipped = prefill -> "
    "KV ship -> decode continuation completed the planned way, "
    "fell_back = any step failed and the request finished via the "
    "splice-recompute continuation (token-identical, prefill paid "
    "again), prefill_only = EOS landed inside the first chunk so "
    "nothing needed shipping.",
    ("outcome",),
)
SERVE_MIGRATIONS = _registry.counter(
    "oim_serve_migrations_total",
    "Live slot migrations (drain/scale-in/eviction, ISSUE 17) by "
    "outcome: migrated = the suspended slot shipped to a sibling and "
    "the stream resumed from its KV (zero recompute of decoded "
    "tokens), fell_back = any step failed and the request finished "
    "via the splice-recompute continuation (token-identical greedy, "
    "prefill paid again), gave_up = no sibling existed to take the "
    "state — the one outcome that loses work.  The outcomes sum to "
    "migrate markers received; a nonzero gave_up during a planned "
    "drain means the fleet was drained below N=2.",
    ("outcome",),
)

# ---------------------------------------------------------------------------
# Per-tenant SLO attribution histograms (ISSUE 9): the engine's phase
# clock (queue → admit → prefill → decode → stream) keyed by the mTLS
# tenant CN the HTTP layer hands in with each request.  Shared
# definitions like the fault-tolerance set so the whole fleet exports
# one series shape; the tenant label value is the peer cert's CN (or
# "anon" on a plain-HTTP deployment).  Phase sums reconcile against
# oim_serve_e2e_seconds by construction (tests assert it): the phases
# partition the request's submit-to-finalize window.

SERVE_QUEUE_WAIT = _registry.histogram(
    "oim_serve_queue_wait_seconds",
    "Submit-to-admission wait per request, by tenant CN: time spent in "
    "the admission queue before a slot opened.  The growing phase under "
    "fleet saturation — compare with oim_serve_prefill_seconds to split "
    "'engine is busy' from 'prefill is slow'.",
    ("tenant",),
)
SERVE_PREFILL = _registry.histogram(
    "oim_serve_prefill_seconds",
    "Prefill latency per request, by tenant CN: first device dispatch "
    "(prefix-cache injection / chunked-prefill segments included) to "
    "first-token readback.  Scales with prompt length; the phase the "
    "prefill/decode disaggregation split will move off decode backends.",
    ("tenant",),
)
SERVE_TPOT = _registry.histogram(
    "oim_serve_tpot_seconds",
    "Time per output token after the first, by tenant CN (decode-phase "
    "wall over tokens-1) — the streaming cadence a client experiences "
    "once tokens flow, vs oim_serve_ttft_seconds for the wait before "
    "them.  Sub-chunk-wall on a healthy chip, so FAST_BUCKETS.",
    ("tenant",),
    buckets=FAST_BUCKETS,
)
SERVE_E2E = _registry.histogram(
    "oim_serve_e2e_seconds",
    "Submit-to-finalize latency per request, by tenant CN and outcome "
    "(ok / deadline / deadline_queue / cancelled / stalled / aborted).  "
    "The per-tenant SLO series; per-phase breakdowns for any slow "
    "request live in GET /debugz/requests and `oimctl requests`.",
    ("tenant", "outcome"),
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
             300.0, 600.0),
)

# ---------------------------------------------------------------------------
# Multi-tenant QoS instruments (ISSUE 16): the enforcement actions the
# policy layer takes, labeled by the TENANT TIER they acted for/against
# (tier, not tenant, to bound cardinality — per-tenant detail lives in
# /v1/stats and `oimctl tenants`), plus the one per-tenant series cheap
# enough to carry the raw CN: generated-token totals, the series quota
# billing and fair-share verification both read.

SERVE_QOS = _registry.counter(
    "oim_serve_qos_total",
    "QoS enforcement actions by tenant tier: admitted = an engine "
    "admission the fair-share scheduler granted, throttled = a request "
    "shed at the router by the tenant's rate/token bucket (shed reason "
    "quota), preempted = an admission that had to park a lower-tier "
    "victim to fit (labeled with the PREEMPTOR's tier), parked_victim "
    "= the other side of that preemption (labeled with the VICTIM's "
    "tier; the slot swaps to host RAM and restores later — never "
    "killed, see oim_serve_kv_tier_moves_total).",
    ("tenant_tier", "action"),
)
SERVE_TENANT_TOKENS = _registry.counter(
    "oim_serve_tenant_tokens_total",
    "Generated (output) tokens per tenant CN, counted at request "
    "finalize.  The consumption series behind token quotas and the "
    "ground truth a fair-share convergence check compares against "
    "policy weights.",
    ("tenant",),
)

# ---------------------------------------------------------------------------
# Fleet-load and autoscaler instruments (ISSUE 8): the serving plane's
# live pressure as the autoscaler sees it, and the control loop's own
# decisions/actions.  Defined here (not in the engine or the autoscaler)
# so the metrics lint covers them and every exporter shares one series
# shape.  The `engine` label key is kept from the original per-engine
# gauges (oim_serve_active_slots predates this module — a silent label
# rename would blank existing dashboards): its value is the engine's
# per-process label when the engine itself exports, and the serve
# backend id when the autoscaler's fleet view does.

SERVE_ACTIVE_SLOTS = _registry.gauge(
    "oim_serve_active_slots",
    "Slots currently decoding, per serving instance (engine label = "
    "in-process engine index, or the backend id in the autoscaler's "
    "fleet view).",
    ("engine",),
)
SERVE_QUEUE_DEPTH = _registry.gauge(
    "oim_serve_queue_depth",
    "Requests waiting for a slot, per serving instance (the admission "
    "backlog the autoscaler's utilization counts as busy work).",
    ("engine",),
)
SERVE_KV_BLOCKS = _registry.gauge(
    "oim_serve_kv_blocks",
    "Paged-KV pool occupancy by block state: free = allocatable now "
    "(the engine's real admission headroom — admissions defer, not "
    "crash, when a request's worst case exceeds it), used = held by at "
    "least one slot or prefix-cache entry, shared = aliased by more "
    "than one owner (HBM the fleet would otherwise hold in duplicate), "
    "host = resident in the host-RAM overflow tier (ISSUE 15: demoted "
    "prefix entries + parked slots — KV preserved beyond HBM, promoted "
    "back on a hit instead of recomputed).  Absent on dense "
    "(non-paged) engines; the host state is absent without "
    "--kv-host-bytes.",
    ("engine", "state"),
)
SERVE_KV_TIER_MOVES = _registry.counter(
    "oim_serve_kv_tier_moves_total",
    "Blocks moved between the HBM pool and the host-RAM overflow tier "
    "by direction: demote = device → host (prefix shortfall / LRU "
    "pressure / slot parking), promote = host → device (prefix hit on "
    "a demoted entry / slot restore).  A promote rate tracking the "
    "demote rate at high kv_fragmentation is the host-tier THRASH "
    "signature (doc/operations.md) — the budget is moving the same "
    "blocks in circles instead of holding working set.",
    ("op",),
)
SERVE_KV_TIER_SECONDS = _registry.counter(
    "oim_serve_kv_tier_seconds_total",
    "Wall seconds spent moving blocks between tiers, by direction "
    "(demote = the batched read_block fetch + host pool write, off "
    "the driver's critical path; promote = the host → device ingest "
    "writes ahead of the tail prefill).  Divide by the matching "
    "oim_serve_kv_tier_moves_total rate for per-block cost; compare "
    "promote cost against oim_serve_prefill_seconds for the "
    "promote-vs-recompute break-even (doc/serving.md).",
    ("op",),
)
SERVE_PREFIX_BYTES_SAVED = _registry.counter(
    "oim_serve_prefix_bytes_saved_total",
    "KV bytes prefix-cache hits reused instead of recomputing, by "
    "savings path: source=alias = a locally stored entry's full "
    "blocks shared copy-free into the admitted slot's table (the PR "
    "10 path), source=fetched = the hit rode an entry installed from "
    "a sibling's exported prefix (ISSUE 14) — bytes this backend "
    "never prefilled at all.  The copy-on-write duplicate of a "
    "partially-covered last block is a real copy and does not count "
    "under either source.",
    ("engine", "source"),
)
SERVE_PREFIX_FETCH_SECONDS = _registry.histogram(
    "oim_serve_prefix_fetch_seconds",
    "Wall time of one router-orchestrated prefix ship (GET "
    "/v1/kv?prefix= off the resident sibling + PUT /v1/kv into the "
    "routed target).  Compare against the donor's "
    "oim_serve_prefill_seconds: a fetch slower than the recompute it "
    "replaces means the crossover guidance in doc/serving.md 'Fleet "
    "prefix residency' wants a higher minimum entry size.",
)
SERVE_PREFIX_FETCH = _registry.counter(
    "oim_serve_prefix_fetch_total",
    "Router-orchestrated prefix ships by outcome: fetched = the "
    "entry landed on the routed target before forwarding, fell_back "
    "= the ship failed and the request recomputed its prefill "
    "(token-identical either way), ineligible = the residency map "
    "advertised an unfetchable entry (dense/kv4 source, no prefix "
    "cache on the target) — persistent ineligible growth means the "
    "fleet mixes layouts the ship protocol refuses.",
    ("outcome",),
)
ROUTE_RESIDENCY_DIGESTS = _registry.gauge(
    "oim_route_residency_digests",
    "Distinct prefix digests in the router's fleet residency map "
    "(union over per-backend load/serve.<id> digest summaries, "
    "refreshed every probe tick).  Zero with prefix caches enabled = "
    "the load schema is not reaching the router (stale publishers, "
    "probe failures); see doc/operations.md 'Fleet prefix residency "
    "incidents'.",
)
AUTOSCALE_DESIRED = _registry.gauge(
    "oim_autoscale_desired_replicas",
    "Replica count the autoscaler's last evaluation wanted the fleet "
    "at (current size +/- the decided step, before cooldown/backoff "
    "gates).  Diverging from the live backend count = actuation is "
    "failing or clamped; see oim_autoscale_actions_total.",
)
AUTOSCALE_ACTIONS = _registry.counter(
    "oim_autoscale_actions_total",
    "Autoscaler actions by direction (out / in / replace) and outcome "
    "(ok / clamped / failed).  clamped = the chip pool was exhausted "
    "(ENOSPC) and the autoscaler backed off instead of crash-looping.",
    ("direction", "outcome"),
)
XLA_COMPILES = _registry.counter(
    "oim_xla_compiles_total",
    "XLA backend compilations in this process, counted via the "
    "jax.monitoring per-compile duration event (installed by "
    "oim_tpu.serve.sentinel at daemon init).  The count is expected "
    "to plateau after warmup; any increase on a serving daemon after "
    "the steady-state latch armed also emits a serve.recompile "
    "flight-recorder event with the active request context — see "
    "doc/operations.md 'Performance forensics'.",
)
XLA_COMPILE_SECONDS = _registry.histogram(
    "oim_xla_compile_seconds",
    "Wall time of each XLA backend compilation.  Milliseconds on the "
    "CPU CI backend, 20-40 s per program on a real TPU — which is why "
    "a single post-warm bucket increment here is a mid-stream stall "
    "worth paging on, not a latency curiosity.",
)
SERVE_REQUEST_RING_DROPPED = _registry.counter(
    "oim_serve_request_ring_dropped_total",
    "Completed requests whose forensic ring entry displaced the "
    "oldest entry (ring full) or was dropped outright.  A steadily "
    "rising rate means the --request-ring window is shorter than the "
    "incident-response lag and slow-request forensics will be missing "
    "their neighborhood; size the ring per doc/operations.md.",
    ("engine",),
)
SERVE_KV_TIER_BYTES = _registry.counter(
    "oim_serve_kv_tier_bytes_total",
    "KV bytes moved between the HBM and host tiers, by op (demote = "
    "HBM→host including park evictions, promote = host→HBM including "
    "unpark restores).  Pair with oim_serve_kv_tier_moves_total for "
    "per-block cost and with oim_serve_kv_tier_seconds for bandwidth; "
    "a demote rate approaching the PCIe budget means the host tier is "
    "thrashing — see doc/operations.md 'KV-tier flow incidents'.",
    ("op",),
)
SERVE_KV_TIER_RESIDENT = _registry.gauge(
    "oim_serve_kv_tier_resident_bytes",
    "KV bytes currently resident per tier (device = HBM block pool "
    "in use, host = overflow/park tier in use).  The fleet sum over "
    "backends is the 'one hierarchical KV store' occupancy that "
    "cache-aware autoscaling consumes (ROADMAP item 5); per backend "
    "it is the denominator for demote/promote flow rates.",
    ("engine", "tier"),
)
SERVE_SLOW_CAPTURES = _registry.counter(
    "oim_serve_slow_captures_total",
    "Tail-latency auto-captures written to the flight dir, by trigger "
    "(e2e = absolute end-to-end threshold, tpot = marginal per-token "
    "EWMA multiple).  Each increment corresponds to one "
    "serve.slow_capture event naming the artifact path; captures are "
    "rate-limited, so this undercounts slow requests — it counts "
    "dumped artifacts.",
    ("engine", "trigger"),
)
PROCESS_RSS = _registry.gauge(
    "oim_process_resident_bytes",
    "Resident set size of this daemon process (from /proc/self/statm; "
    "ru_maxrss high-water fallback where /proc is unavailable).  On a "
    "serving host this is dominated by host-tier KV and the runtime "
    "heap, NOT device HBM — compare with "
    "oim_serve_kv_tier_resident_bytes{tier=\"host\"} to attribute "
    "growth.",
)
PROCESS_CPU_SECONDS = _registry.gauge(
    "oim_process_cpu_seconds",
    "Cumulative user+system CPU seconds consumed by this process "
    "(os.times).  Exposed as a gauge because the value is read, not "
    "accumulated, at scrape time; rate() over it still yields CPU "
    "utilisation.",
)
PROCESS_THREADS = _registry.gauge(
    "oim_process_threads",
    "Live Python threads in this process.  A serving daemon has a "
    "small, stable set (driver, HTTP, streamers, host-tier flusher); "
    "unbounded growth means a leaked per-request or per-capture "
    "thread.",
)
PROCESS_GC_PAUSE_SECONDS = _registry.counter(
    "oim_process_gc_pause_seconds_total",
    "Cumulative wall time spent inside CPython garbage collections "
    "(gc.callbacks start→stop).  GC pauses on the driver thread are "
    "invisible to per-phase request tracing but show up as TPOT "
    "outliers — correlate spikes here with serve.slow_capture events.",
)
PROCESS_GC_COLLECTIONS = _registry.counter(
    "oim_process_gc_collections_total",
    "CPython garbage collections observed via gc.callbacks, by "
    "generation.",
    ("generation",),
)


_process_metrics_state = {"installed": False}
_process_metrics_lock = threading.Lock()


def install_process_metrics() -> bool:
    """Bind the ``oim_process_*`` self-telemetry gauges to this process
    (RSS, CPU seconds, thread count, GC pauses) — idempotent, stdlib
    only.  Called by every daemon at init (MetricsServer.start() calls
    it too, so any daemon with a scrape endpoint gets it for free);
    returns False when already installed."""
    with _process_metrics_lock:
        if _process_metrics_state["installed"]:
            return False
        _process_metrics_state["installed"] = True

    import gc
    import os

    try:
        page = os.sysconf("SC_PAGE_SIZE")
    except (AttributeError, ValueError, OSError):
        page = 4096

    def _rss() -> float:
        try:
            with open("/proc/self/statm") as f:
                return float(int(f.read().split()[1]) * page)
        except (OSError, ValueError, IndexError):
            try:
                import resource

                # ru_maxrss is a KiB high-water mark, not instantaneous
                # RSS — good enough as a fallback ceiling.
                return float(
                    resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
                )
            except Exception:
                return 0.0

    def _cpu() -> float:
        t = os.times()
        return float(t.user + t.system)

    PROCESS_RSS.set_function(_rss)
    PROCESS_CPU_SECONDS.set_function(_cpu)
    PROCESS_THREADS.set_function(lambda: float(threading.active_count()))

    # gc.callbacks run synchronously on the collecting thread while it
    # holds the GIL, and "start"/"stop" for one collection cannot
    # interleave with another — a single shared t0 slot is race-free.
    gc_t0 = [0.0]

    def _gc_callback(phase: str, info: dict) -> None:
        if phase == "start":
            gc_t0[0] = time.perf_counter()
        elif phase == "stop":
            PROCESS_GC_PAUSE_SECONDS.inc(by=time.perf_counter() - gc_t0[0])
            PROCESS_GC_COLLECTIONS.inc(str(info.get("generation", "")))

    gc.callbacks.append(_gc_callback)
    return True


EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def write_exposition(handler, registry_: MetricsRegistry | None = None) -> None:
    """Write the Prometheus text exposition as the response to an
    http.server request ``handler`` — THE one definition of the scrape
    response, shared by MetricsServer and any component embedding
    /metrics in its own HTTP surface (e.g. oim-serve)."""
    body = (registry_ or _registry).render().encode()
    handler.send_response(200)
    handler.send_header("Content-Type", EXPOSITION_CONTENT_TYPE)
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


# ---------------------------------------------------------------------------
# gRPC server instrumentation


class MetricsServerInterceptor(ObservingServerInterceptor):
    """Counts and times every handled RPC:

    - ``oim_rpc_handled_total{component,method,code}``
    - ``oim_rpc_handling_seconds{component,method}`` histogram
    """

    def __init__(self, component: str, registry_: MetricsRegistry | None = None):
        self.component = component
        reg = registry_ or _registry
        self.handled = reg.counter(
            "oim_rpc_handled_total",
            "RPCs handled, by gRPC method and status code.",
            ("component", "method", "code"),
        )
        self.latency = reg.histogram(
            "oim_rpc_handling_seconds",
            "Server-side RPC handling latency.",
            ("component", "method"),
        )

    @contextlib.contextmanager
    def observe(self, method, handler_call_details, request_or_iterator, context):
        def code_of(exc: BaseException | None) -> str:
            code = getattr(context, "code", lambda: None)()
            if code is None and exc is None:
                return grpc.StatusCode.OK.name
            if isinstance(code, grpc.StatusCode):
                return code.name
            return grpc.StatusCode.UNKNOWN.name

        start = time.perf_counter()
        try:
            yield None
        except BaseException as exc:
            self.handled.inc(self.component, method, code_of(exc))
            self.latency.observe(
                time.perf_counter() - start, self.component, method
            )
            raise
        self.handled.inc(self.component, method, code_of(None))
        self.latency.observe(
            time.perf_counter() - start, self.component, method
        )


# ---------------------------------------------------------------------------
# /metrics HTTP exposition


def _split_host_port(address: str) -> tuple[str, str]:
    """``host:port`` → (host, port), IPv6-bracket-aware.

    ``[::1]:9090`` yields ``::1`` (ThreadingHTTPServer rejects the
    brackets); a bare ``9090`` (no colon) is an error rather than silently
    binding all interfaces; ``:9090`` keeps the Go empty-host convention.
    """
    host, sep, port = address.rpartition(":")
    if not sep:
        raise ValueError(
            f"metrics address {address!r} must be host:port (':{address}' "
            "for all interfaces)"
        )
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    elif ":" in host:
        raise ValueError(
            f"IPv6 metrics address must be bracketed: '[{host}]:{port}'"
        )
    if port and not port.isdigit():
        raise ValueError(f"metrics address {address!r} has non-numeric port")
    return host, port


class MetricsServer:
    """Minimal scrape endpoint: ``GET /metrics`` on a host:port, plus
    ``GET /debugz`` — the live flight-recorder rings as JSON
    (oim_tpu.common.events), so any daemon's recent event history is one
    curl away during an incident."""

    def __init__(
        self, address: str = "127.0.0.1:0",
        registry_: MetricsRegistry | None = None,
    ):
        host, port = _split_host_port(address)
        reg = registry_ or _registry

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API)
                path = self.path.split("?", 1)[0]
                if path == "/debugz":
                    # Imported lazily: events imports this module.
                    import json as json_mod

                    from oim_tpu.common import events as events_mod

                    body = json_mod.dumps(events_mod.snapshot()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                write_exposition(self, reg)

            def log_message(self, *args):  # quiet
                pass

        # Go convention: an empty host (":9090") binds all interfaces.
        server_cls = http.server.ThreadingHTTPServer
        if ":" in host:  # IPv6 literal (unbracketed by _split_host_port)
            import socket as _socket

            class _V6Server(http.server.ThreadingHTTPServer):
                address_family = _socket.AF_INET6

            server_cls = _V6Server
        self._httpd = server_cls((host, int(port or 0)), Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "MetricsServer":
        # Any daemon exposing a scrape endpoint gets the oim_process_*
        # self-telemetry series for free (idempotent per process).
        install_process_metrics()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
