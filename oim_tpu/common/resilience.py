"""Shared control-plane resilience: retries, deadlines, circuit breaking.

The reference design routes every control operation over a registry/proxy
hop (host ↔ card cannot talk directly, reference spec.md:33-56), which
makes transient RPC failure the *normal* failure mode rather than the
exceptional one.  This module is THE one definition of how the control
plane reacts to it, shared by the CSI remote backend, the agent JSON-RPC
client, the controller/serve registry heartbeats, and the health
reporter, so their behavior under faults can never diverge:

- ``RetryPolicy``: declarative exponential backoff with full jitter
  (AWS-style ``uniform(0, min(cap, base*mult^n))``), a per-attempt
  timeout and an overall deadline.  Clock, sleep and RNG are injectable
  so tests are deterministic — no wall time, no flakes.
- ``retryable(exc)``: the status classifier.  UNAVAILABLE and
  DEADLINE_EXCEEDED mean "the hop failed, the operation may not have";
  INVALID_ARGUMENT / FAILED_PRECONDITION / ALREADY_EXISTS mean the
  *request* is wrong and retrying can only repeat the answer.
  Transport-level breaks (EPIPE/ECONNRESET/refused dial) are retryable.
- ``CircuitBreaker``: per-target closed → open after N consecutive
  failures → half-open probe after a cooldown.  An open breaker fails
  fast instead of hammering a dead peer with full retry ladders.
- ``call_with_retry``: the loop tying the three together, emitting
  ``oim_rpc_attempts_total`` / ``oim_rpc_retries_total`` /
  ``oim_rpc_latency_seconds`` (instrument definitions live in
  oim_tpu.common.metrics so every daemon exports the same series).

Retrying a mutation is only safe against an idempotent server; the
controller's MapVolume/UnmapVolume are volume_id-keyed idempotent
(oim_tpu/controller/controller.py) precisely so this layer may re-send
them after an ambiguous failure (request executed, reply lost).
"""

from __future__ import annotations

import errno
import os
import random
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Callable

import grpc

from oim_tpu import log
from oim_tpu.common import metrics

# Status codes where the *hop* failed (peer unreachable, deadline blown)
# and a retry can plausibly land: the request itself was never judged.
RETRYABLE_STATUS = frozenset(
    {grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.DEADLINE_EXCEEDED}
)

# Transport errnos that mean "the connection died, not the request":
# broken pipe / reset (peer restarted mid-call), refused dial.
_RETRYABLE_ERRNOS = frozenset(
    {
        errno.EPIPE,
        errno.ECONNRESET,
        errno.ECONNREFUSED,
        errno.ECONNABORTED,
    }
)

# Additionally retryable when the caller is DIALING a unix socket it owns
# the lifecycle relationship with: a missing socket file just means the
# daemon is mid-restart (it unlinks on stop, binds on start).  NOT part
# of the general classifier — an ENOENT from, say, a mistyped TLS cert
# path is a deterministic misconfiguration that must surface immediately,
# not be retried into a flaky-looking ladder.
_DIAL_RETRYABLE_ERRNOS = _RETRYABLE_ERRNOS | {errno.ENOENT, errno.EAGAIN}


def _raw_code(exc: BaseException) -> grpc.StatusCode | None:
    """``exc.code()`` if it yields a real StatusCode, else None — the
    crash-proof primitive under status_of/peer_judged."""
    code = None
    code_fn = getattr(exc, "code", None)
    if callable(code_fn):
        try:
            code = code_fn()
        except Exception:
            code = None
    return code if isinstance(code, grpc.StatusCode) else None


def status_of(exc: BaseException) -> grpc.StatusCode:
    """The gRPC status of an exception, None-safe.

    A *locally* raised RpcError (channel torn down mid-call, interceptor
    failure) can return ``None`` from ``exc.code()``; classifying — and
    formatting — must not crash on it, so it maps to UNKNOWN.
    """
    if isinstance(exc, grpc.RpcError):
        return _raw_code(exc) or grpc.StatusCode.UNKNOWN
    return grpc.StatusCode.UNKNOWN


def details_of(exc: BaseException) -> str:
    """Human-readable detail for an RpcError, None-details-safe (a
    locally raised RpcError may have no ``details`` or return None)."""
    try:
        details = getattr(exc, "details", lambda: None)()
    except Exception:
        details = None
    return str(details or exc or "RPC failed")


def error_text(exc: BaseException) -> str:
    """``STATUS: details`` for an RpcError, None-code/None-details-safe —
    THE one formatter for surfacing gRPC failures to humans (a locally
    raised RpcError crashes the naive ``exc.code().name`` pattern)."""
    return f"{status_of(exc).name}: {details_of(exc)}"


def peer_judged(exc: BaseException) -> bool:
    """Did the peer actually answer ``exc``?  True for application-level
    errors and server-judged gRPC statuses; False for a *locally* raised
    RpcError (raw ``code()`` is None — the channel died before any
    answer) and for transport errors, which prove nothing about the peer
    being alive."""
    if isinstance(exc, grpc.RpcError):
        return _raw_code(exc) is not None
    return not isinstance(exc, (ConnectionError, TimeoutError, OSError))


def retryable(exc: BaseException) -> bool:
    """Should the shared policy re-send after ``exc``?

    gRPC: only hop-failure statuses (RETRYABLE_STATUS).  Transport:
    connection breaks and timeouts.  Everything else — including
    application errors like AgentError — is the peer's *answer* and is
    final.
    """
    if isinstance(exc, grpc.RpcError):
        return status_of(exc) in RETRYABLE_STATUS
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    if isinstance(exc, OSError):
        return exc.errno in _RETRYABLE_ERRNOS
    return False


def retryable_dial(exc: BaseException) -> bool:
    """``retryable`` widened for clients that re-dial a unix socket each
    attempt (the agent client): an absent socket file is the daemon
    restarting, so ENOENT/EAGAIN are hop failures there."""
    if isinstance(exc, OSError) and not isinstance(
        exc, (ConnectionError, TimeoutError)
    ):
        return exc.errno in _DIAL_RETRYABLE_ERRNOS
    return retryable(exc)


# ---------------------------------------------------------------------------
# Retry policy


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        log.current().warning("invalid env knob", name=name, value=raw)
        return default


@dataclass(frozen=True)
class RetryPolicy:
    """Declarative bounded-retry policy.

    ``max_attempts`` counts the first try: 1 disables retries entirely
    (the chaos suite proves the soak *fails* at 1 — retries, not luck).
    ``overall_deadline_s`` caps the whole ladder from the first attempt;
    backoff sleeps are truncated so the ladder never overshoots it.
    ``clock``/``sleep``/``rng`` are injectable for deterministic tests.
    """

    max_attempts: int = 4
    initial_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    multiplier: float = 2.0
    per_attempt_timeout_s: float | None = None
    overall_deadline_s: float | None = None
    clock: Callable[[], float] = time.monotonic
    sleep: Callable[[float], None] = time.sleep
    rng: random.Random = field(default_factory=random.Random)

    @classmethod
    def from_env(cls, **overrides) -> "RetryPolicy":
        """Operator knobs (doc/operations.md): OIM_RETRY_MAX_ATTEMPTS,
        OIM_RETRY_INITIAL_BACKOFF_S, OIM_RETRY_MAX_BACKOFF_S,
        OIM_RETRY_MULTIPLIER, OIM_RETRY_DEADLINE_S (0 = unbounded)."""
        deadline = _env_float("OIM_RETRY_DEADLINE_S", 0.0)
        policy = cls(
            max_attempts=max(1, int(_env_float("OIM_RETRY_MAX_ATTEMPTS", 4))),
            initial_backoff_s=_env_float("OIM_RETRY_INITIAL_BACKOFF_S", 0.05),
            max_backoff_s=_env_float("OIM_RETRY_MAX_BACKOFF_S", 2.0),
            multiplier=_env_float("OIM_RETRY_MULTIPLIER", 2.0),
            overall_deadline_s=deadline if deadline > 0 else None,
        )
        return replace(policy, **overrides) if overrides else policy

    @classmethod
    def one_shot(cls) -> "RetryPolicy":
        """No retries — the pre-resilience behavior, kept constructible
        so the chaos suite can prove retries are what saves the soak."""
        return cls(max_attempts=1)

    @classmethod
    def for_heartbeat(cls, period_s: float) -> "RetryPolicy":
        """Env-tuned policy for a periodic beat: the whole ladder is
        capped at 80% of the period so one slow ladder can never pile
        onto the next beat — shared by the controller/serve address
        heartbeats and the health publish loop."""
        return cls.from_env(overall_deadline_s=max(period_s * 0.8, 0.1))

    def base_backoff(self, attempt: int) -> float:
        """Pre-jitter ceiling before retry ``attempt`` (1 = first retry):
        ``min(max, initial * multiplier**(attempt-1))``."""
        raw = self.initial_backoff_s * (self.multiplier ** (attempt - 1))
        return min(self.max_backoff_s, raw)

    def backoff(self, attempt: int) -> float:
        """Full-jitter sleep before retry ``attempt``: uniform over
        ``[0, base_backoff(attempt)]`` (decorrelates a thundering herd of
        hosts all retrying the same dead registry)."""
        return self.rng.uniform(0.0, self.base_backoff(attempt))

    def attempt_timeout(self, deadline: float | None) -> float | None:
        """Per-attempt RPC timeout, truncated to the overall deadline."""
        remaining = None
        if deadline is not None:
            remaining = max(deadline - self.clock(), 0.001)
        if self.per_attempt_timeout_s is None:
            return remaining
        if remaining is None:
            return self.per_attempt_timeout_s
        return min(self.per_attempt_timeout_s, remaining)


# ---------------------------------------------------------------------------
# Circuit breaker

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class BreakerOpenError(Exception):
    """Fail-fast rejection: the breaker for ``target`` is open.  Not
    retryable by design — the point is to STOP hammering the peer."""

    def __init__(self, target: str, retry_in_s: float):
        super().__init__(
            f"circuit breaker for {target!r} is open "
            f"(probe in {retry_in_s:.1f}s)"
        )
        self.target = target
        self.retry_in_s = retry_in_s


class CircuitBreaker:
    """Per-target consecutive-failure breaker.

    closed --N consecutive failures--> open --cooldown--> half-open
    (exactly one probe admitted) --success--> closed / --failure--> open.

    Counts *operations* (a whole retry ladder), not attempts: callers
    record once per call_with_retry outcome, so the threshold reads as
    "N straight failed operations", independent of the retry budget.
    Transitions are observable via
    ``oim_breaker_transitions_total{target,state}``.
    """

    def __init__(
        self,
        target: str,
        failure_threshold: int = 5,
        reset_timeout_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.target = target
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        # Generation token: bumps on every state transition.  allow()
        # hands it to the operation; record_* with a stale token is
        # ignored, so an operation admitted under an old state (e.g. one
        # that hung through close→open→half-open) can neither steal nor
        # resolve a later probe's slot, nor re-open a breaker on evidence
        # that predates it.
        self._generation = 0

    @classmethod
    def from_env(cls, target: str, **overrides) -> "CircuitBreaker":
        """Operator knobs: OIM_BREAKER_FAILURES, OIM_BREAKER_RESET_S."""
        kwargs = dict(
            failure_threshold=max(1, int(_env_float("OIM_BREAKER_FAILURES", 5))),
            reset_timeout_s=_env_float("OIM_BREAKER_RESET_S", 10.0),
        )
        kwargs.update(overrides)
        return cls(target, **kwargs)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition_locked(self, state: str) -> None:
        if self._state == state:
            return
        prev = self._state
        self._state = state
        self._generation += 1
        metrics.BREAKER_TRANSITIONS.inc(self.target, state)
        log.current().info(
            "breaker transition", target=self.target, state=state
        )
        # resilience → events bridge: breaker transitions are exactly the
        # state changes an incident timeline needs (opening = the moment
        # a peer was judged dead).  Imported lazily — events sits above
        # this module in the layering.
        try:
            from oim_tpu.common import events

            events.emit(
                "breaker.transition",
                component="resilience",
                severity=events.WARNING if state == OPEN else events.INFO,
                subject=self.target,
                **{"from": prev, "to": state},
            )
        except Exception:  # the journal must never break the breaker
            pass

    def allow(self) -> int:
        """Gate one operation; raises BreakerOpenError when open (and
        while a half-open probe is already in flight).  Returns the
        generation token to pass back to ``record_*`` so a stale
        operation cannot corrupt later probe accounting."""
        with self._lock:
            if self._state == CLOSED:
                return self._generation
            now = self.clock()
            if self._state == OPEN:
                elapsed = now - self._opened_at
                if elapsed < self.reset_timeout_s:
                    raise BreakerOpenError(
                        self.target, self.reset_timeout_s - elapsed
                    )
                self._transition_locked(HALF_OPEN)
                self._probing = True
                return self._generation
            # HALF_OPEN: exactly one in-flight probe.
            if self._probing:
                raise BreakerOpenError(self.target, self.reset_timeout_s)
            self._probing = True
            return self._generation

    def _stale_locked(self, token: int | None) -> bool:
        return token is not None and token != self._generation

    def record_success(self, token: int | None = None) -> None:
        with self._lock:
            if self._stale_locked(token):
                return
            self._failures = 0
            self._probing = False
            self._transition_locked(CLOSED)

    def record_abandoned(self, token: int | None = None) -> None:
        """The operation ended without a verdict on the peer (interrupt,
        shutdown): release an in-flight half-open probe slot but change
        no state — neither evidence of life nor of death."""
        with self._lock:
            if self._stale_locked(token):
                return
            self._probing = False

    def record_failure(self, token: int | None = None) -> None:
        with self._lock:
            if self._stale_locked(token):
                return
            self._probing = False
            if self._state == HALF_OPEN:
                self._opened_at = self.clock()
                self._transition_locked(OPEN)
                return
            self._failures += 1
            if self._state == CLOSED and self._failures >= self.failure_threshold:
                self._opened_at = self.clock()
                self._transition_locked(OPEN)


# ---------------------------------------------------------------------------
# The retry loop


@dataclass(frozen=True)
class Attempt:
    """What one attempt of ``call_with_retry`` hands the callable."""

    number: int  # 1-based
    timeout: float | None  # per-attempt RPC timeout (None = caller default)

    def clamped(self, default: float = 10.0, floor: float = 0.1) -> float:
        """Per-attempt RPC timeout as a concrete number: the ladder's
        remaining budget clamped to [floor, default] — THE one clamp the
        heartbeat/publish hops share, so a hanging peer can never stall
        an operation past the deadline its policy promises."""
        if self.timeout is None:
            return default
        return min(default, max(self.timeout, floor))

    def budget_clamp(
        self, clock: Callable[[], float] = time.monotonic
    ) -> Callable[..., float]:
        """For attempts that issue SEVERAL RPCs: a ``t(default)`` callable
        that re-derives the remaining budget at each call, so the whole
        attempt — not each RPC — fits the ladder deadline (N hanging
        RPCs must not each burn the full per-attempt clamp).  Pass the
        policy's clock so fake-clock tests stay deterministic."""
        deadline = None if self.timeout is None else clock() + self.timeout

        def clamp(default: float = 10.0, floor: float = 0.1) -> float:
            if deadline is None:
                return default
            return min(default, max(deadline - clock(), floor))

        return clamp


def call_with_retry(
    fn: Callable[[Attempt], object],
    policy: RetryPolicy,
    *,
    component: str,
    op: str,
    classify: Callable[[BaseException], bool] = retryable,
    breaker: CircuitBreaker | None = None,
    on_retry: Callable[[BaseException, int], None] | None = None,
):
    """Run ``fn(attempt)`` under ``policy``; returns its result or raises
    the final error.

    - ``classify(exc)`` decides retryability (default: the shared
      classifier).  Non-retryable errors propagate immediately.
    - ``breaker`` (optional) gates the whole operation: an open breaker
      raises BreakerOpenError with NO attempt made, and the operation's
      outcome feeds back exactly once.
    - ``on_retry(exc, attempt)`` runs before each re-attempt — the hook
      where the CSI backend invalidates its cached channel so the retry
      re-dials instead of reusing a dead socket.
    """
    token = breaker.allow() if breaker is not None else None
    start = policy.clock()
    deadline = (
        start + policy.overall_deadline_s
        if policy.overall_deadline_s is not None
        else None
    )
    attempt = 0
    try:
        while True:
            attempt += 1
            try:
                result = fn(Attempt(attempt, policy.attempt_timeout(deadline)))
            except Exception as exc:
                now = policy.clock()
                if not classify(exc):
                    metrics.RPC_ATTEMPTS.inc(component, op, "fatal")
                    metrics.RPC_LATENCY.observe(now - start, component, op)
                    if breaker is not None:
                        # A non-retryable *answer* proves the peer is
                        # alive and judging requests — but only if the
                        # peer actually answered: a locally raised
                        # RpcError (code()=None) is hop death and feeds
                        # the failure streak instead.
                        if peer_judged(exc):
                            breaker.record_success(token)
                        else:
                            breaker.record_failure(token)
                    raise
                metrics.RPC_ATTEMPTS.inc(component, op, "retryable")
                out_of_budget = (
                    attempt >= policy.max_attempts
                    or (deadline is not None and now >= deadline)
                )
                if out_of_budget:
                    metrics.RPC_LATENCY.observe(now - start, component, op)
                    if breaker is not None:
                        breaker.record_failure(token)
                    raise
                delay = policy.backoff(attempt)
                if deadline is not None:
                    delay = min(delay, max(deadline - now, 0.0))
                log.current().debug(
                    "retrying",
                    component=component,
                    op=op,
                    attempt=attempt,
                    delay=round(delay, 4),
                    error=str(exc),
                )
                metrics.RPC_RETRIES.inc(component, op)
                if on_retry is not None:
                    on_retry(exc, attempt)
                if delay > 0:
                    policy.sleep(delay)
                continue
            metrics.RPC_ATTEMPTS.inc(component, op, "ok")
            metrics.RPC_LATENCY.observe(policy.clock() - start, component, op)
            if breaker is not None:
                breaker.record_success(token)
            return result
    except BaseException as exc:
        # Interrupt/exit — from the attempt, the backoff sleep, or an
        # on_retry hook: no verdict on the peer, but a half-open probe
        # slot must not stay claimed forever.
        if breaker is not None and not isinstance(exc, Exception):
            breaker.record_abandoned(token)
        raise


class ConnCache:
    """One lazily dialed, droppable, close-latched connection.

    The dial-outside-the-lock discipline, in one place, for every
    component that caches a single eagerly-connecting client (the
    controller's agent/scrape connections, the health reporter's
    telemetry connection): ``get()`` reads the cached connection under
    the lock but runs ``dial`` OUTSIDE it, so a wedged peer costs the
    dialing thread its socket timeout — never ``close()`` or other
    threads contending for the cache.  Racing dialers are resolved
    under the lock (the first installed wins; the loser's connection
    is closed).  ``close()`` latches: a dial that was in flight when
    the cache closed is closed on arrival instead of being installed,
    so shutdown cannot leak the late connection — and later ``get()``
    calls raise instead of silently re-dialing (the same latch
    discipline as the agent ``Client``).
    """

    def __init__(self, dial: Callable):
        self._dial = dial
        self._lock = threading.Lock()
        self._conn = None
        self._closed = False

    def _swallow_close(self, conn) -> None:
        try:
            conn.close()
        except Exception:
            pass

    def get(self):
        """The cached connection, dialing one if absent.  Raises
        RuntimeError once the cache is closed."""
        with self._lock:
            if self._closed:
                raise RuntimeError("connection cache is closed")
            conn = self._conn
        if conn is not None:
            return conn
        fresh = self._dial()
        loser = None
        with self._lock:
            if self._closed:
                loser = fresh
            elif self._conn is None:
                conn = self._conn = fresh
            else:
                loser, conn = fresh, self._conn
        if loser is not None:
            self._swallow_close(loser)
        if conn is None:
            raise RuntimeError("connection cache is closed")
        return conn

    def peek(self):
        """The cached connection or None — never dials (fault-injection
        tests use this to reach the live connection and sever it)."""
        with self._lock:
            return self._conn

    def drop(self) -> None:
        """Close and forget the cached connection; the next ``get()``
        starts from a fresh dial."""
        with self._lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            self._swallow_close(conn)

    def close(self) -> None:
        """Idempotent: latch closed, then drop whatever is cached."""
        with self._lock:
            self._closed = True
        self.drop()
