"""Registry path sanitation (≙ reference pkg/oim-common/path.go:23-38).

Registry keys are ``/``-separated paths like ``controller-1/address``.  Path
elements may contain only ``[a-zA-Z0-9._-]`` and may not be empty, ``.`` or
``..``; leading/trailing/duplicate slashes are normalized away.
"""

from __future__ import annotations

import re

_ELEMENT_RE = re.compile(r"^[a-zA-Z0-9._-]+$")


def clean_path(path: str) -> str:
    elements = [e for e in path.split("/") if e != ""]
    if not elements:
        raise ValueError("empty registry path")
    for e in elements:
        if e in (".", ".."):
            raise ValueError(f"invalid registry path element {e!r} in {path!r}")
        if not _ELEMENT_RE.match(e):
            raise ValueError(f"invalid characters in registry path element {e!r}")
    return "/".join(elements)


def split_path(path: str) -> list[str]:
    return clean_path(path).split("/")
