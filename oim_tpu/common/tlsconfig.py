"""mTLS configuration with CommonName pinning.

≙ reference pkg/oim-common/grpc.go:77-127 (``LoadTLS``/``LoadTLSConfig``):
every control-plane connection is mutually authenticated against one CA; the
*client* pins the expected server identity by overriding the TLS server name
to the peer's CN, and a *server* may additionally restrict which peer CN is
allowed to call it (the reference's ``VerifyPeerCertificate``; here a gRPC
server interceptor, see ``peer_check_interceptor``).
"""

from __future__ import annotations

from dataclasses import dataclass

import grpc


@dataclass
class TLSConfig:
    ca_pem: bytes
    cert_pem: bytes
    key_pem: bytes
    # Expected remote CommonName. As a client: pinned via TLS server-name
    # override. As a server: "" accepts any CA-signed peer (per-method checks
    # happen later, like the registry, reference cmd/oim-registry/main.go:53).
    peer_name: str = ""

    def server_credentials(self) -> grpc.ServerCredentials:
        return grpc.ssl_server_credentials(
            [(self.key_pem, self.cert_pem)],
            root_certificates=self.ca_pem,
            require_client_auth=True,
        )

    def channel_credentials(self) -> grpc.ChannelCredentials:
        return grpc.ssl_channel_credentials(
            root_certificates=self.ca_pem,
            private_key=self.key_pem,
            certificate_chain=self.cert_pem,
        )

    def channel_options(self) -> list[tuple[str, str]]:
        if not self.peer_name:
            return []
        return [("grpc.ssl_target_name_override", self.peer_name)]

    def with_peer(self, peer_name: str) -> "TLSConfig":
        return TLSConfig(self.ca_pem, self.cert_pem, self.key_pem, peer_name)


def load_tls(
    ca_file: str, cert_file: str, key_file: str, peer_name: str = ""
) -> TLSConfig:
    """Load PEM files (≙ ``LoadTLS``; key/cert naming follows setup-ca.sh)."""
    with open(ca_file, "rb") as f:
        ca = f.read()
    with open(cert_file, "rb") as f:
        cert = f.read()
    with open(key_file, "rb") as f:
        key = f.read()
    return TLSConfig(ca, cert, key, peer_name)


def peer_common_name(context: grpc.ServicerContext) -> str | None:
    """CommonName of the authenticated client, or None when unauthenticated.

    Source of truth for the registry's per-method authorization (reference
    pkg/oim-registry/registry.go:100-109 checks this CN).
    """
    auth = context.auth_context()
    names = auth.get("x509_common_name")
    if not names:
        return None
    return names[0].decode()
