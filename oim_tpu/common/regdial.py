"""Registry channel construction — THE one definition of how clients
dial the registry (fresh per-operation channel, CN pinned to
``component.registry`` under mTLS), shared by the controller heartbeat,
the serve-instance heartbeat, and router discovery so their dialing can
never diverge (≙ the per-operation connection discipline of
/root/reference/pkg/oim-controller/controller.go:448-453)."""

from __future__ import annotations

from contextlib import contextmanager

REGISTRY_CN = "component.registry"


@contextmanager
def registry_channel(registry_address: str, tls=None):
    """Yield a fresh gRPC channel to the registry; closes on exit."""
    import grpc

    from oim_tpu.common import endpoint as ep

    target = ep.parse(registry_address).grpc_target()
    if tls is not None:
        pinned = tls.with_peer(REGISTRY_CN)
        channel = grpc.secure_channel(
            target,
            pinned.channel_credentials(),
            options=pinned.channel_options(),
        )
    else:
        channel = grpc.insecure_channel(target)
    try:
        yield channel
    finally:
        channel.close()
