"""Registry channel construction — THE one definition of how clients
dial the registry (fresh per-operation channel, CN pinned to
``component.registry`` under mTLS), shared by the controller heartbeat,
the serve-instance heartbeat, and router discovery so their dialing can
never diverge (≙ the per-operation connection discipline of
/root/reference/pkg/oim-controller/controller.go:448-453)."""

from __future__ import annotations

from contextlib import contextmanager

REGISTRY_CN = "component.registry"

# Long-lived streams (WatchValues) can sit idle for hours on a stable
# fleet; HTTP/2 keepalive pings detect a middlebox silently dropping the
# connection (NAT/conntrack idle eviction sends no RST), turning an
# invisible freeze into an RpcError the reopen loop handles.  Harmless
# on short-lived per-operation channels.
KEEPALIVE_OPTIONS = (
    ("grpc.keepalive_time_ms", 30_000),
    ("grpc.keepalive_timeout_ms", 10_000),
    ("grpc.keepalive_permit_without_calls", 1),
    ("grpc.http2.max_pings_without_data", 0),
)


@contextmanager
def registry_channel(registry_address: str, tls=None):
    """Yield a fresh gRPC channel to the registry; closes on exit."""
    import grpc

    from oim_tpu.common import endpoint as ep

    target = ep.parse(registry_address).grpc_target()
    if tls is not None:
        pinned = tls.with_peer(REGISTRY_CN)
        channel = grpc.secure_channel(
            target,
            pinned.channel_credentials(),
            options=tuple(pinned.channel_options()) + KEEPALIVE_OPTIONS,
        )
    else:
        channel = grpc.insecure_channel(target, options=KEEPALIVE_OPTIONS)
    try:
        yield channel
    finally:
        channel.close()
