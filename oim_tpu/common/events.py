"""Flight recorder: structured, trace-correlated event journal.

The reference had no event record at all — state transitions lived in
unstructured logs and vanished when they scrolled (its README "traces"
are correlated log lines, reference README.md:455-495).  This module is
the third observability pillar next to the working traces
(oim_tpu.common.tracing) and metrics (oim_tpu.common.metrics): a durable,
queryable answer to the incident question *"what happened to volume X
between map and stage, and when?"*

Design:

- **Typed events.**  Every event carries ``component`` (emitting daemon),
  ``kind`` (dotted vocabulary, e.g. ``volume.map`` / ``breaker.transition``),
  ``severity``, ``subject`` (the volume/chip/controller it is about), the
  ``trace_id`` captured from the active span (so events join the trace
  tree for free), a per-recorder monotonic ``seq``, wall-clock ``ts`` and
  free-form key/value ``fields``.
- **Flight recorder.**  One bounded in-memory ring per component
  (drop-oldest, counted by ``oim_events_dropped_total``): every process
  is introspectable with zero configuration via ``/debugz`` on its
  MetricsServer, and a crash hook dumps all rings to a JSON file on a
  fatal error — the black box survives the incident that needed it.
- **Durable WARNING+ publication.**  A ``RegistryEventPublisher`` mirrors
  WARNING/ERROR events into the registry under leased
  ``events/<source>/<seq>`` keys (TTL-GC'd by the lease sweeper;
  authz-scoped like ``health/`` — a component may only write its own
  subtree), so ``oimctl events`` sees the fleet's recent anomalies
  without dialing every daemon.
- **Volume-lifecycle SLOs.**  ``phase()``/``begin_e2e()``/``end_e2e()``
  feed ``oim_volume_lifecycle_seconds{phase=map|stage|publish|e2e}`` and
  emit the per-phase events ``oimctl events --volume X`` renders as an
  ordered, trace-linked timeline with durations.

Emission is cheap (one lock, one deque append, a counter bump) so the
control plane can narrate itself unconditionally; sinks run outside the
ring lock and a failing sink costs one log line, never the caller.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

from oim_tpu import log
from oim_tpu.common import metrics, tracing

# ---------------------------------------------------------------------------
# Vocabulary

DEBUG = "DEBUG"
INFO = "INFO"
WARNING = "WARNING"
ERROR = "ERROR"
SEVERITIES = (DEBUG, INFO, WARNING, ERROR)
_SEVERITY_RANK = {s: i for i, s in enumerate(SEVERITIES)}

EVENTS_PREFIX = "events"

DEFAULT_CAPACITY = 512


def severity_at_least(severity: str, floor: str) -> bool:
    return _SEVERITY_RANK.get(severity, 0) >= _SEVERITY_RANK.get(floor, 0)


def event_key(source: str, seq: int | str) -> str:
    """Registry key for a durably published event — the ``health/``-shaped
    keyspace: ``events/<source>/<seq>``, where ``source`` is the writer's
    TLS CommonName (``controller.<id>``, ``serve.<id>``, ...) so the
    registry can authz-scope each component to its own subtree."""
    return f"{EVENTS_PREFIX}/{source}/{seq}"


def parse_event_path(path: str) -> tuple[str, str] | None:
    parts = path.split("/")
    if len(parts) == 3 and parts[0] == EVENTS_PREFIX:
        return parts[1], parts[2]
    return None


# ---------------------------------------------------------------------------
# Instruments (process registry — every daemon exports identical series)

EVENTS_TOTAL = metrics.registry().counter(
    "oim_events_total",
    "Flight-recorder events emitted, by component, kind and severity.",
    ("component", "kind", "severity"),
)
EVENTS_DROPPED = metrics.registry().counter(
    "oim_events_dropped_total",
    "Events evicted from a full flight-recorder ring (drop-oldest).",
    ("component",),
)
EVENTS_PUBLISHED = metrics.registry().counter(
    "oim_events_published_total",
    "Durable WARNING+ event publications to the registry, by outcome "
    "(ok / error / dropped — dropped means the publish queue overflowed).",
    ("source", "outcome"),
)
LIFECYCLE = metrics.registry().histogram(
    "oim_volume_lifecycle_seconds",
    "Volume lifecycle phase latency: map (the MapVolume hop inside "
    "NodeStage), stage (whole NodeStageVolume), publish "
    "(NodePublishVolume), e2e (stage begin through publish done).",
    ("phase",),
)


# ---------------------------------------------------------------------------
# Event model


@dataclass(frozen=True)
class Event:
    component: str
    kind: str
    severity: str
    subject: str
    trace_id: str
    seq: int
    ts: float  # wall clock (UNIX seconds)
    fields: dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        return {
            "component": self.component,
            "kind": self.kind,
            "severity": self.severity,
            "subject": self.subject,
            "trace_id": self.trace_id,
            "seq": self.seq,
            "ts": self.ts,
            "fields": self.fields,
        }

    @classmethod
    def from_json(cls, obj: dict[str, Any]) -> "Event":
        if not isinstance(obj, dict):
            # Callers catch (TypeError, ValueError): a foreign file whose
            # entries are not objects must yield a skip, never a crash.
            raise TypeError(f"event must be a JSON object, got {type(obj)}")
        return cls(
            component=str(obj.get("component", "?")),
            kind=str(obj.get("kind", "?")),
            severity=str(obj.get("severity", INFO)),
            subject=str(obj.get("subject", "")),
            trace_id=str(obj.get("trace_id", "")),
            seq=int(obj.get("seq", 0)),
            ts=float(obj.get("ts", 0.0)),
            fields=obj.get("fields", {}) if isinstance(obj.get("fields"), dict) else {},
        )


# ---------------------------------------------------------------------------
# Recorders

_sinks_lock = threading.Lock()
_sinks: list[Callable[[Event], None]] = []


class FlightRecorder:
    """Bounded per-component event ring (the "flight recorder")."""

    def __init__(self, component: str, capacity: int = DEFAULT_CAPACITY):
        self.component = component
        self._lock = threading.Lock()
        self._ring: deque[Event] = deque(maxlen=capacity)
        self._seq = 0

    def emit(
        self,
        kind: str,
        severity: str = INFO,
        subject: str = "",
        **fields: Any,
    ) -> Event:
        ctx = tracing.current_context()
        with self._lock:
            self._seq += 1
            event = Event(
                component=self.component,
                kind=kind,
                severity=severity,
                subject=subject,
                trace_id=ctx.trace_id if ctx is not None else "",
                seq=self._seq,
                ts=time.time(),
                fields=fields,
            )
            dropped = len(self._ring) == self._ring.maxlen
            self._ring.append(event)
        EVENTS_TOTAL.inc(self.component, kind, severity)
        if dropped:
            EVENTS_DROPPED.inc(self.component)
        with _sinks_lock:
            sinks = list(_sinks)
        for sink in sinks:  # outside the ring lock: sinks may do IO
            try:
                sink(event)
            except Exception as exc:
                log.current().error(
                    "event sink failed", kind=kind, error=str(exc)
                )
        return event

    def events(self) -> list[Event]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_recorders_lock = threading.Lock()
_recorders: dict[str, FlightRecorder] = {}
_default_component = [""]


def recorder(component: str = "") -> FlightRecorder:
    """The process recorder for ``component`` (created on first use).
    Empty means the process default set by ``init()``."""
    name = component or _default_component[0]
    with _recorders_lock:
        rec = _recorders.get(name)
        if rec is None:
            rec = _recorders[name] = FlightRecorder(name)
        return rec


def init(component: str) -> FlightRecorder:
    """Set the process-default component (each daemon main calls this
    next to ``tracing.init``) and return its recorder."""
    _default_component[0] = component
    return recorder(component)


def emit(
    kind: str,
    component: str = "",
    severity: str = INFO,
    subject: str = "",
    **fields: Any,
) -> Event:
    """Emit on the component's recorder (default: the process default)."""
    return recorder(component).emit(kind, severity=severity, subject=subject, **fields)


def add_sink(fn: Callable[[Event], None]) -> None:
    with _sinks_lock:
        if fn not in _sinks:
            _sinks.append(fn)


def remove_sink(fn: Callable[[Event], None]) -> None:
    with _sinks_lock:
        if fn in _sinks:
            _sinks.remove(fn)


def all_events() -> list[Event]:
    """Every recorder's ring, merged in wall-clock order."""
    with _recorders_lock:
        recs = list(_recorders.values())
    merged: list[Event] = []
    for rec in recs:
        merged.extend(rec.events())
    merged.sort(key=lambda e: (e.ts, e.component, e.seq))
    return merged


def clear_all() -> None:
    """Empty every ring (test isolation; recorders stay registered)."""
    with _recorders_lock:
        recs = list(_recorders.values())
    for rec in recs:
        rec.clear()


# ---------------------------------------------------------------------------
# Snapshots: /debugz + crash dump share one JSON shape


def snapshot() -> dict[str, Any]:
    """The live flight-recorder contents as one JSON document — served by
    ``/debugz`` on the MetricsServer and written by the crash hook, so
    ``oimctl events`` reads both with one loader."""
    return {
        "generated_at": time.time(),
        "pid": os.getpid(),
        "events": [e.to_json() for e in all_events()],
    }


def dump(path: str) -> str:
    """Write the snapshot to ``path`` atomically-ish (tmp + rename)."""
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(snapshot(), f, separators=(",", ":"))
    os.replace(tmp, path)
    return path


def events_from_doc(doc: Any) -> list[Event]:
    """Events from a parsed snapshot document (``dump()`` file or a
    ``/debugz`` response body).  Tolerant of foreign/partial content —
    an operator pointing ``oimctl events`` at the wrong file or URL gets
    an empty timeline, not a stack trace.  THE one parser for both
    sources, so their tolerance can never drift."""
    entries = doc.get("events") if isinstance(doc, dict) else None
    out: list[Event] = []
    for obj in entries if isinstance(entries, list) else []:
        try:
            out.append(Event.from_json(obj))
        except (TypeError, ValueError):
            continue
    return out


def load_dump(path: str) -> list[Event]:
    """Events from a crash dump / ``/debugz`` capture file."""
    with open(path) as f:
        return events_from_doc(json.load(f))


# ---------------------------------------------------------------------------
# Crash hook

_crash_lock = threading.Lock()
_crash_state: dict[str, Any] = {"installed": False, "dir": "", "prev": None, "prev_threading": None}


def flight_dir() -> str:
    """THE directory for forensic artifacts (crash dumps, slow-capture
    dumps, on-demand profiler traces): the crash hook's configured dir,
    else ``$OIM_FLIGHT_DIR``, else /tmp.  One resolution order so an
    operator who set the flight dir finds every artifact kind in it."""
    return (
        _crash_state["dir"]
        or os.environ.get("OIM_FLIGHT_DIR")
        or "/tmp"
    )


def crash_dump_path() -> str:
    return os.path.join(
        flight_dir(), f"oim-flight-{os.getpid()}-{int(time.time())}.json"
    )


def _dump_on_crash(exc_type, exc_value) -> str | None:
    if exc_type is not None and issubclass(
        exc_type, (KeyboardInterrupt, SystemExit)
    ):
        return None  # operator stop, not a crash
    try:
        emit(
            "crash",
            severity=ERROR,
            error=f"{getattr(exc_type, '__name__', exc_type)}: {exc_value}",
        )
        path = crash_dump_path()
        dump(path)
        log.current().error("flight recorder dumped", path=path)
        return path
    except Exception:
        return None  # the dump must never mask the original crash


def install_crash_hook(directory: str = "") -> None:
    """Dump every ring to a JSON file on an uncaught exception (main
    thread AND worker threads), then chain to the previous hooks.
    ``directory`` defaults to ``$OIM_FLIGHT_DIR`` or /tmp.  Idempotent."""
    with _crash_lock:
        if directory:
            _crash_state["dir"] = directory
        if _crash_state["installed"]:
            return
        prev_sys = sys.excepthook
        prev_threading = threading.excepthook
        _crash_state["prev"] = prev_sys
        _crash_state["prev_threading"] = prev_threading

        def hook(exc_type, exc_value, exc_tb):
            _dump_on_crash(exc_type, exc_value)
            prev_sys(exc_type, exc_value, exc_tb)

        def thread_hook(args):
            _dump_on_crash(args.exc_type, args.exc_value)
            prev_threading(args)

        sys.excepthook = hook
        threading.excepthook = thread_hook
        _crash_state["installed"] = True


def uninstall_crash_hook() -> None:
    """Restore the pre-install hooks (test hygiene)."""
    with _crash_lock:
        if not _crash_state["installed"]:
            return
        sys.excepthook = _crash_state["prev"]
        threading.excepthook = _crash_state["prev_threading"]
        _crash_state["installed"] = False


# ---------------------------------------------------------------------------
# Volume-lifecycle SLO timeline

_e2e_lock = threading.Lock()
_e2e_starts: dict[str, float] = {}  # volume → monotonic stage-begin
_E2E_BOUND = 4096  # a leak of abandoned stages must stay bounded


@contextlib.contextmanager
def phase(volume: str, phase_name: str, component: str = ""):
    """Time one lifecycle phase: observes
    ``oim_volume_lifecycle_seconds{phase=...}`` and emits the
    ``volume.<phase>`` event (with ``duration_ms``) the timeline
    renderer shows.  An exception emits ``volume.<phase>.failed`` at
    ERROR instead and re-raises."""
    t0 = time.perf_counter()
    try:
        yield
    except BaseException as exc:
        emit(
            f"volume.{phase_name}.failed",
            component=component,
            severity=ERROR,
            subject=volume,
            phase=phase_name,
            duration_ms=round((time.perf_counter() - t0) * 1e3, 3),
            error=str(exc),
        )
        raise
    dt = time.perf_counter() - t0
    LIFECYCLE.observe(dt, phase_name)
    emit(
        f"volume.{phase_name}",
        component=component,
        subject=volume,
        phase=phase_name,
        duration_ms=round(dt * 1e3, 3),
    )


def begin_e2e(volume: str) -> None:
    """Mark the start of a volume's map→stage→publish flow (NodeStage
    entry).  Re-staging restarts the clock."""
    with _e2e_lock:
        if len(_e2e_starts) >= _E2E_BOUND and volume not in _e2e_starts:
            oldest = min(_e2e_starts, key=_e2e_starts.get)
            del _e2e_starts[oldest]
        _e2e_starts[volume] = time.perf_counter()


def end_e2e(volume: str, component: str = "") -> None:
    """Complete the flow (publish done): observes ``phase="e2e"`` and
    emits ``volume.e2e``.  No-op when no stage began (idempotent
    re-publish)."""
    with _e2e_lock:
        t0 = _e2e_starts.pop(volume, None)
    if t0 is None:
        return
    dt = time.perf_counter() - t0
    LIFECYCLE.observe(dt, "e2e")
    emit(
        "volume.e2e",
        component=component,
        subject=volume,
        phase="e2e",
        duration_ms=round(dt * 1e3, 3),
    )


def abandon_e2e(volume: str) -> None:
    """Forget a flow that will never publish (unstage/teardown)."""
    with _e2e_lock:
        _e2e_starts.pop(volume, None)


# ---------------------------------------------------------------------------
# Durable publication: WARNING+ → leased registry keys


class RegistryEventPublisher:
    """Mirrors WARNING+ events into ``events/<source>/<seq>`` leased
    registry keys, on its own thread so emission never blocks on the
    registry hop.  Best-effort durability: the queue is bounded
    (drop-oldest, counted), a failed publish drops its batch — the ring
    stays the source of truth, the registry copy is the fleet-wide view.

    ``source`` must be the publisher's TLS CommonName (e.g.
    ``controller.<id>``): the registry's authz allows each identity to
    write only its own ``events/<cn>/*`` subtree (the ``health/``
    least-privilege shape).  ``tls`` may be the config or a zero-arg
    loader (the CSI driver reloads material per dial).  The registry
    process itself passes ``db`` instead of an address and publishes by
    storing directly — no RPC, no self-dial, same key shape and TTL."""

    def __init__(
        self,
        source: str,
        registry_address: str = "",
        tls=None,
        min_severity: str = WARNING,
        ttl_seconds: float = 900.0,
        capacity: int = 256,
        db=None,
    ) -> None:
        if bool(registry_address) == (db is not None):
            raise ValueError("pass exactly one of registry_address / db")
        self.source = source
        self.registry_address = registry_address
        self.tls = tls
        self.min_severity = min_severity
        self.ttl_seconds = ttl_seconds
        self.db = db
        self._cond = threading.Condition()
        self._queue: deque[Event] = deque(maxlen=capacity)
        self._stop = False
        self._thread: threading.Thread | None = None
        # Publication counter, NOT the event's per-recorder seq (two
        # recorders' event #5 must land under distinct keys) — seeded
        # from the wall clock so a restarted daemon's keys continue
        # after its previous run's instead of overwriting records still
        # inside their TTL.
        self._pub_seq = time.time_ns()

    # -- sink side (any emitting thread) -----------------------------------

    def _sink(self, event: Event) -> None:
        if not severity_at_least(event.severity, self.min_severity):
            return
        with self._cond:
            if self._stop:
                return
            if len(self._queue) == self._queue.maxlen:
                EVENTS_PUBLISHED.inc(self.source, "dropped")
            self._queue.append(event)
            self._cond.notify()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RegistryEventPublisher":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = False
        add_sink(self._sink)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"events-publish-{self.source}"
        )
        self._thread.start()
        return self

    def close(self) -> None:
        """Idempotent: detach the sink, wake and join the drain thread."""
        remove_sink(self._sink)
        with self._cond:
            self._stop = True
            self._cond.notify()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            self._thread = None

    # -- drain thread ------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait()
                batch = list(self._queue)
                self._queue.clear()
                stopping = self._stop
            if batch:
                self._publish(batch)
            if stopping:
                return

    def _publish(self, batch: list[Event]) -> None:
        try:
            if self.db is not None:
                for event in batch:
                    self._pub_seq += 1
                    self.db.store(
                        event_key(self.source, self._pub_seq),
                        json.dumps(event.to_json(), separators=(",", ":")),
                        ttl=max(1, int(self.ttl_seconds)),
                    )
                    EVENTS_PUBLISHED.inc(self.source, "ok")
                return
            from oim_tpu.common.regdial import registry_channel
            from oim_tpu.spec import REGISTRY, oim_pb2

            tls = self.tls() if callable(self.tls) else self.tls
            with registry_channel(self.registry_address, tls) as channel:
                stub = REGISTRY.stub(channel)
                for event in batch:
                    self._pub_seq += 1
                    stub.SetValue(
                        oim_pb2.SetValueRequest(
                            value=oim_pb2.Value(
                                path=event_key(self.source, self._pub_seq),
                                value=json.dumps(
                                    event.to_json(), separators=(",", ":")
                                ),
                            ),
                            ttl_seconds=max(1, int(self.ttl_seconds)),
                        ),
                        timeout=5,
                    )
                    EVENTS_PUBLISHED.inc(self.source, "ok")
        except Exception as exc:
            # One failed hop costs this batch, never the daemon: the
            # events are still in the ring + /debugz + crash dump.
            EVENTS_PUBLISHED.inc(self.source, "error")
            log.current().warning(
                "event publish failed",
                source=self.source,
                batch=len(batch),
                error=str(exc),
            )


# ---------------------------------------------------------------------------
# Timeline rendering (the ``oimctl events`` backend)


def _match(event: Event, volume: str, component: str, kind: str) -> bool:
    if volume and event.subject != volume:
        return False
    if component and event.component != component:
        return False
    if kind and not event.kind.startswith(kind):
        return False
    return True


def filter_events(
    evts: Iterable[Event],
    volume: str = "",
    component: str = "",
    kind: str = "",
) -> list[Event]:
    out = [e for e in evts if _match(e, volume, component, kind)]
    out.sort(key=lambda e: (e.ts, e.component, e.seq))
    return out


def render_event(event: Event) -> str:
    """One event as one line (the ``oimctl events --follow`` format)."""
    try:
        dur = f"{float(event.fields.get('duration_ms')):9.2f}ms"
    except (TypeError, ValueError):
        # A foreign/hand-written event with a junk duration must cost
        # its duration column, not the whole timeline.
        dur = " " * 11
    extras = " ".join(
        f"{k}={v}"
        for k, v in sorted(event.fields.items())
        if k not in ("duration_ms", "phase")
    )
    trace = f" trace={event.trace_id[:8]}" if event.trace_id else ""
    return (
        f"{dur} {event.severity:<7} {event.component:<16} "
        f"{event.kind:<28} {event.subject:<16}{trace}"
        + (f"  {extras}" if extras else "")
    )


def render_timeline(
    evts: Iterable[Event],
    volume: str = "",
    component: str = "",
    kind: str = "",
) -> str:
    """The merged, ordered timeline: offset from the first matching
    event, per-phase duration when the event carries one, severity,
    component, kind, subject, short trace id and the remaining fields —
    the flight-recorder answer to "what happened to volume X, and
    when"."""
    matched = filter_events(evts, volume=volume, component=component, kind=kind)
    if not matched:
        return "(no matching events)"
    t0 = matched[0].ts
    return "\n".join(
        f"+{event.ts - t0:9.3f}s {render_event(event)}" for event in matched
    )
