"""Endpoint parsing for ``(unix|tcp|tcp4|tcp6)://`` addresses.

≙ reference pkg/oim-common/server.go:28-40 (``ParseEndpoint``), adapted to the
address syntaxes grpc-python expects (``unix:/path`` and ``host:port``).
"""

from __future__ import annotations

from dataclasses import dataclass

_SCHEMES = ("unix", "tcp", "tcp4", "tcp6")


@dataclass(frozen=True)
class Endpoint:
    scheme: str  # unix | tcp | tcp4 | tcp6
    address: str  # filesystem path for unix, host:port for tcp

    @property
    def is_unix(self) -> bool:
        return self.scheme == "unix"

    def grpc_target(self) -> str:
        """Channel target string for grpc.*_channel."""
        if self.is_unix:
            return f"unix:{self.address}"
        return self.address

    def grpc_listen(self) -> str:
        """Listen address for grpc.Server.add_*_port."""
        if self.is_unix:
            return f"unix:{self.address}"
        return self.address

    def __str__(self) -> str:
        return f"{self.scheme}://{self.address}"


def parse(endpoint: str) -> Endpoint:
    for scheme in _SCHEMES:
        prefix = scheme + "://"
        if endpoint.startswith(prefix):
            address = endpoint[len(prefix) :]
            if not address:
                raise ValueError(f"empty address in endpoint {endpoint!r}")
            return Endpoint(scheme, address)
    if "://" in endpoint:
        raise ValueError(f"unsupported endpoint scheme in {endpoint!r}")
    if not endpoint:
        raise ValueError("empty endpoint")
    # Bare host:port defaults to tcp, mirroring the reference's tolerance.
    return Endpoint("tcp", endpoint)
