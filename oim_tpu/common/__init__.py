"""Shared control-plane infrastructure (≙ reference pkg/oim-common)."""
