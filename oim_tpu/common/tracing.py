"""Distributed tracing across the control plane.

The reference *scaffolded* tracing but shipped it disabled: ``InitTracer``
returns a nop closer and the Jaeger/OpenTracing gRPC interceptors exist
only as commented-out code (reference pkg/oim-common/tracing.go:17-21,
:153-214, grpc.go:57-62, server.go:79-84); what its README shows as a
"trace" of one CreateVolume→NodePublish flow is correlated *logs*
(reference README.md:455-495).  This module is the working version of
that intent, kept dependency-free:

- Span context travels in gRPC metadata as a W3C ``traceparent``
  (``00-<32 hex trace-id>-<16 hex span-id>-01``), so one trace id links
  kubelet-facing CSI calls, the registry proxy hop, and the controller.
- ``TraceServerInterceptor`` opens a server span per handled RPC and tags
  the context logger with the short trace id — log lines and spans
  correlate the way the reference's method-tagged context logger lines
  do (reference pkg/oim-common/tracing.go:134-140).
- ``trace_channel`` wraps a client channel so outgoing calls open client
  spans and inject the current context (what the commented-out
  ``OpenTracingClientInterceptor`` would have done).
- Spans land in a per-process in-memory ring and, when configured, an
  append-only JSONL file.  Each daemon writes its own file; ``oimctl
  trace`` merges files and renders the cross-process tree.

Python's ``contextvars`` carries the active span the same way the logger
travels (see oim_tpu.log), so nesting needs no explicit plumbing.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import secrets
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterator

import grpc

from oim_tpu import log
from oim_tpu.common import metrics
from oim_tpu.common.interceptors import ObservingServerInterceptor

TRACEPARENT_KEY = "traceparent"

# Ring evictions: a long-lived daemon's collector is bounded (drop-oldest),
# and silent truncation would read as "nothing happened before X" during
# an incident — the counter makes the loss visible per component.
SPANS_DROPPED = metrics.registry().counter(
    "oim_trace_spans_dropped_total",
    "Spans evicted from a full collector ring (drop-oldest).",
    ("component",),
)

# ---------------------------------------------------------------------------
# Span model + context propagation


@dataclass(frozen=True)
class SpanContext:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


def parse_traceparent(value: str) -> SpanContext | None:
    """Parse a W3C traceparent; None for anything malformed (a bad peer
    header must never break the RPC it rode in on)."""
    parts = value.split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id)


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str  # "" for a root span
    name: str  # operation, e.g. "/csi.v1.Node/NodeStageVolume"
    component: str  # process/service, e.g. "oim-csi-driver"
    start_ns: int
    end_ns: int = 0
    status: str = "ok"
    attrs: dict[str, Any] = field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def to_json(self) -> dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "component": self.component,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "status": self.status,
            "attrs": self.attrs,
        }

    @classmethod
    def from_json(cls, obj: dict[str, Any]) -> "Span":
        return cls(
            trace_id=obj["trace_id"],
            span_id=obj["span_id"],
            parent_id=obj.get("parent_id", ""),
            name=obj.get("name", "?"),
            component=obj.get("component", "?"),
            start_ns=obj.get("start_ns", 0),
            end_ns=obj.get("end_ns", 0),
            status=obj.get("status", "ok"),
            attrs=obj.get("attrs", {}),
        )


_current: contextvars.ContextVar[SpanContext | None] = contextvars.ContextVar(
    "oim_tpu_span", default=None
)


def current_context() -> SpanContext | None:
    return _current.get()


# ---------------------------------------------------------------------------
# Collector


class Collector:
    """Per-process span sink: bounded in-memory ring + optional JSONL file.

    The ring makes every process introspectable without configuration
    (tests, embedders); the file is the cross-process story — one file
    per daemon, merged offline by ``oimctl trace`` / ``load_jsonl``.
    """

    def __init__(self, component: str = "", path: str | None = None,
                 capacity: int = 4096) -> None:
        self.component = component
        self._lock = threading.Lock()
        self._ring: deque[Span] = deque(maxlen=capacity)
        self._file = open(path, "a", buffering=1) if path else None

    def record(self, span: Span) -> None:
        # Serialize outside the lock: every RPC thread funnels through
        # here, and only the ring append + write need the mutex.
        line = json.dumps(span.to_json()) + "\n" if self._file else None
        with self._lock:
            dropped = len(self._ring) == self._ring.maxlen
            self._ring.append(span)
            if self._file is not None and line is not None:
                self._file.write(line)
        if dropped:
            SPANS_DROPPED.inc(self.component)

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


_collector = Collector()


def init(component: str = "", path: str | None = None) -> Collector:
    """Configure the process collector (``--trace-file`` / $OIM_TRACE_FILE).

    ``path`` may also come from the environment so any daemon can be
    traced without a flag change."""
    global _collector
    old = _collector
    path = path or os.environ.get("OIM_TRACE_FILE") or None
    _collector = Collector(component=component, path=path)
    old.close()
    return _collector


def collector() -> Collector:
    return _collector


# ---------------------------------------------------------------------------
# Span creation


def _new_trace_id() -> str:
    return secrets.token_hex(16)


def _new_span_id() -> str:
    return secrets.token_hex(8)


def new_trace_id() -> str:
    """Public id mint for callers recording spans manually."""
    return _new_trace_id()


def record_span(
    name: str,
    *,
    component: str = "",
    trace_id: str = "",
    parent_id: str = "",
    start_ns: int,
    end_ns: int,
    status: str = "ok",
    **attrs: Any,
) -> Span:
    """Record one already-measured interval as a span.

    ``start_span`` models the common case — a span whose lifetime IS a
    ``with`` block on one thread.  Phase spans measured from host-side
    timestamps (the serve engine's queue/admit/prefill/decode phases,
    which begin and end across driver-loop iterations) cannot ride a
    context manager; they are reconstructed after the fact from their
    recorded boundaries and handed in whole here.  An empty
    ``trace_id`` mints a fresh trace (the span becomes a root)."""
    span = Span(
        trace_id=trace_id or _new_trace_id(),
        span_id=_new_span_id(),
        parent_id=parent_id,
        name=name,
        component=component or _collector.component,
        start_ns=start_ns,
        end_ns=end_ns,
        status=status,
        attrs=dict(attrs),
    )
    _collector.record(span)
    return span


@contextlib.contextmanager
def start_span(
    name: str,
    component: str = "",
    parent: SpanContext | None = None,
    **attrs: Any,
) -> Iterator[Span]:
    """Open a span as a child of ``parent`` (default: the context span,
    else a new root), make it current for the block, record on exit.
    An exception marks status=error and re-raises."""
    parent = parent if parent is not None else _current.get()
    trace_id = parent.trace_id if parent else _new_trace_id()
    span = Span(
        trace_id=trace_id,
        span_id=_new_span_id(),
        parent_id=parent.span_id if parent else "",
        name=name,
        component=component or _collector.component,
        start_ns=time.time_ns(),
        attrs=dict(attrs),
    )
    token = _current.set(SpanContext(trace_id, span.span_id))
    try:
        yield span
    except BaseException as exc:
        span.status = f"error: {type(exc).__name__}"
        raise
    finally:
        _current.reset(token)
        span.end_ns = time.time_ns()
        _collector.record(span)


def inject(
    metadata=None, ctx: SpanContext | None = None
) -> list[tuple[str, str]]:
    """Metadata with the span context (default: the current one) appended
    and any stale ``traceparent`` replaced — what proxies must do before
    forwarding."""
    out = [
        (k, v) for k, v in (metadata or ()) if k.lower() != TRACEPARENT_KEY
    ]
    ctx = ctx if ctx is not None else _current.get()
    if ctx is not None:
        out.append((TRACEPARENT_KEY, ctx.traceparent()))
    return out


def extract(metadata) -> SpanContext | None:
    for key, value in metadata or ():
        if key.lower() == TRACEPARENT_KEY:
            return parse_traceparent(value)
    return None


# ---------------------------------------------------------------------------
# gRPC server side


class TraceServerInterceptor(ObservingServerInterceptor):
    """Opens a server span per RPC, parented on the caller's traceparent,
    and tags the context logger with the short trace id so log lines and
    spans correlate."""

    def __init__(self, component: str = "") -> None:
        self.component = component

    @contextlib.contextmanager
    def observe(self, method, handler_call_details, request_or_iterator, context):
        parent = extract(handler_call_details.invocation_metadata)
        with start_span(
            method, component=self.component, parent=parent, kind="server"
        ) as span:
            with log.with_fields(trace=span.trace_id[:8]):
                yield None


# ---------------------------------------------------------------------------
# gRPC client side


class _CallDetails(grpc.ClientCallDetails):
    def __init__(self, base, metadata):
        self.method = base.method
        self.timeout = base.timeout
        self.metadata = metadata
        self.credentials = base.credentials
        self.wait_for_ready = getattr(base, "wait_for_ready", None)
        self.compression = getattr(base, "compression", None)


class TracingClientInterceptor(
    grpc.UnaryUnaryClientInterceptor,
    grpc.UnaryStreamClientInterceptor,
    grpc.StreamUnaryClientInterceptor,
    grpc.StreamStreamClientInterceptor,
):
    """Client half: a ``client`` span per outgoing call with the context
    injected.  The span closes when the call completes (done-callback),
    falling back to call initiation for transports that don't expose one."""

    def __init__(self, component: str = "") -> None:
        self.component = component

    def _call(self, continuation, details, request_or_iterator):
        parent = _current.get()
        span = Span(
            trace_id=parent.trace_id if parent else _new_trace_id(),
            span_id=_new_span_id(),
            parent_id=parent.span_id if parent else "",
            name=details.method,
            component=self.component or _collector.component,
            start_ns=time.time_ns(),
            attrs={"kind": "client"},
        )
        metadata = inject(
            details.metadata, SpanContext(span.trace_id, span.span_id)
        )
        done = threading.Event()

        def finish(call=None):
            if done.is_set():
                return
            done.set()
            span.end_ns = time.time_ns()
            if call is not None:
                try:
                    if call.code() is not grpc.StatusCode.OK:
                        span.status = f"error: {call.code().name}"
                except Exception:
                    pass
            _collector.record(span)

        try:
            call = continuation(
                _CallDetails(details, metadata), request_or_iterator
            )
        except BaseException as exc:
            span.status = f"error: {type(exc).__name__}"
            finish()
            raise
        add_done = getattr(call, "add_done_callback", None)
        if add_done is not None:
            add_done(finish)
        else:  # pragma: no cover - grpc always exposes it today
            finish()
        return call

    def intercept_unary_unary(self, continuation, client_call_details, request):
        return self._call(continuation, client_call_details, request)

    def intercept_unary_stream(self, continuation, client_call_details, request):
        return self._call(continuation, client_call_details, request)

    def intercept_stream_unary(
        self, continuation, client_call_details, request_iterator
    ):
        return self._call(continuation, client_call_details, request_iterator)

    def intercept_stream_stream(
        self, continuation, client_call_details, request_iterator
    ):
        return self._call(continuation, client_call_details, request_iterator)


def trace_channel(channel: grpc.Channel, component: str = "") -> grpc.Channel:
    """Wrap a channel so calls through it are traced + propagated."""
    return grpc.intercept_channel(channel, TracingClientInterceptor(component))


# ---------------------------------------------------------------------------
# Offline: merge + render (the ``oimctl trace`` backend)


def load_jsonl(paths: list[str]) -> list[Span]:
    spans: list[Span] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    spans.append(Span.from_json(json.loads(line)))
                except (ValueError, KeyError, TypeError):
                    continue  # torn write at the file tail
    return spans


def render_traces(spans: list[Span]) -> str:
    """ASCII tree per trace, children indented under parents, ordered by
    start time — the working version of the cross-component trace the
    reference README renders from correlated logs (README.md:455-495)."""
    by_trace: dict[str, list[Span]] = {}
    for span in spans:
        by_trace.setdefault(span.trace_id, []).append(span)
    out: list[str] = []
    for trace_id in sorted(
        by_trace, key=lambda t: min(s.start_ns for s in by_trace[t])
    ):
        members = by_trace[trace_id]
        members.sort(key=lambda s: s.start_ns)
        ids = {s.span_id for s in members}
        children: dict[str, list[Span]] = {}
        roots: list[Span] = []
        for span in members:
            if span.parent_id and span.parent_id in ids:
                children.setdefault(span.parent_id, []).append(span)
            else:
                roots.append(span)
        t0 = members[0].start_ns
        out.append(f"trace {trace_id}")

        def walk(span: Span, depth: int) -> None:
            offset_ms = (span.start_ns - t0) / 1e6
            status = "" if span.status == "ok" else f"  [{span.status}]"
            out.append(
                f"  {'  ' * depth}+{offset_ms:8.2f}ms "
                f"{span.duration_ms:8.2f}ms  {span.component}  "
                f"{span.name}{status}"
            )
            for child in children.get(span.span_id, ()):
                walk(child, depth + 1)

        for root in roots:
            walk(root, 0)
    return "\n".join(out)
