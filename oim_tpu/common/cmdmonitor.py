"""Child-process death detection via an inherited pipe.

≙ reference pkg/oim-common/cmdmonitor.go:14-51: the parent creates a pipe and
passes the write end to the child; because the child never writes, the read
end sees EOF exactly when every holder of the write end (i.e. the child and
any of its descendants that inherited it) has exited — detecting death without
reaping and regardless of who the child's parent is.
"""

from __future__ import annotations

import os
import select
import threading
from typing import Callable


class CmdMonitor:
    def __init__(self) -> None:
        self._r, self._w = os.pipe()
        os.set_inheritable(self._w, True)
        self._closed = False

    @property
    def child_fd(self) -> int:
        """Pass this in ``subprocess.Popen(..., pass_fds=[monitor.child_fd])``."""
        return self._w

    def after_spawn(self) -> None:
        """Close the parent's copy of the write end; must be called once the
        child has been spawned, otherwise EOF never arrives."""
        if not self._closed:
            os.close(self._w)
            self._closed = True

    def dead(self, timeout: float = 0.0) -> bool:
        """True once the child (and inheritors) have exited."""
        r, _, _ = select.select([self._r], [], [], timeout)
        if not r:
            return False
        return os.read(self._r, 1) == b""

    def watch(self, callback: Callable[[], None]) -> threading.Thread:
        """Invoke ``callback`` from a daemon thread when the child dies."""

        def run() -> None:
            while True:
                r, _, _ = select.select([self._r], [], [], None)
                if r and os.read(self._r, 1) == b"":
                    callback()
                    return

        t = threading.Thread(target=run, daemon=True, name="cmdmonitor")
        t.start()
        return t

    def close(self) -> None:
        self.after_spawn()
        os.close(self._r)
