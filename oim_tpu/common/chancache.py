"""gRPC channel reuse with rotation-correct invalidation.

The reference dials per call so that rotated TLS keys and moved
controllers are picked up without restarts (reference remote.go:101-114,
registry.go:206-210) — correct, but it puts a TCP + TLS + HTTP/2
handshake on every control-plane operation.  This cache keeps those
semantics while dropping the per-call handshake: the caller supplies a
*fingerprint* (TLS material + target address) with every acquire; a hit
with an unchanged fingerprint reuses the live channel, any change closes
and re-dials.  TLS files are still read per call — reading PEMs is
microseconds; the handshake was the milliseconds.

Channels idle longer than ``max_idle_s`` are closed opportunistically,
preserving the reference's "short-lived, infrequent connections" stance
(reference README.md:47-49) for quiet periods while making bursts (a pod
churn, a benchmark) fast.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Hashable

import grpc

# Options every cached channel should dial with: a cached channel must
# recover from a server restart about as fast as dial-per-call did, so
# cap gRPC's reconnect backoff (default grows to ~2 min) — the server
# being *down* then surfaces as fast UNAVAILABLE failures and the first
# call after it returns reconnects within ~2 s, no invalidation needed.
RECONNECT_OPTIONS: list[tuple[str, int]] = [
    ("grpc.initial_reconnect_backoff_ms", 200),
    ("grpc.min_reconnect_backoff_ms", 200),
    ("grpc.max_reconnect_backoff_ms", 2000),
]


class ChannelCache:
    def __init__(
        self, max_idle_s: float = 60.0, retire_grace_s: float = 120.0
    ) -> None:
        self.max_idle_s = max_idle_s
        # Evicted/invalidated channels are *retired*, not closed: another
        # thread may still be mid-RPC on them, and grpc.Channel.close()
        # cancels in-flight calls.  Retired channels close once the grace
        # (longer than any control-plane call timeout) has passed.
        # CONSTRAINT: idle timing runs from the last get(), not the last
        # RPC — any single call (notably a proxied inbound stream) that
        # outlives max_idle_s + retire_grace_s can have its channel closed
        # mid-call by an unrelated acquire.  Keep the sum above the longest
        # stream deadline the server allows, or raise retire_grace_s.
        self.retire_grace_s = retire_grace_s
        self._lock = threading.Lock()
        self._entries: dict[
            Hashable, tuple[Hashable, grpc.Channel, float]
        ] = {}
        self._retired: list[tuple[grpc.Channel, float]] = []
        # Churn counter: bumps every time a LIVE cached channel is torn
        # down (invalidate of an existing entry, fingerprint-change
        # re-dial, or idle eviction).  Regression guard for "a heartbeat
        # re-put of an unchanged address must not churn the proxy
        # channel" (registry._on_address_event) — reuse is free, churn
        # is observable.
        self.churn = 0

    def _retire_locked(self, channel: grpc.Channel, now: float) -> None:
        self._retired.append((channel, now))

    def _reap_locked(self, now: float) -> list[grpc.Channel]:
        ripe = [ch for ch, t in self._retired if now - t > self.retire_grace_s]
        self._retired = [
            (ch, t) for ch, t in self._retired
            if now - t <= self.retire_grace_s
        ]
        return ripe

    def get(
        self,
        key: Hashable,
        fingerprint: Hashable,
        dial: Callable[[], grpc.Channel],
    ) -> grpc.Channel:
        """A live channel for ``key``; re-dialed iff ``fingerprint``
        changed since the last acquire (or the entry idled out)."""
        now = time.monotonic()
        with self._lock:
            # Idle sweep covers the requested key too: after a quiet
            # period its old channel is retired and the call below
            # re-dials fresh — the documented "short-lived connections
            # when infrequent" stance.
            for k in [
                k
                for k, (_, _, used) in self._entries.items()
                if now - used > self.max_idle_s
            ]:
                self._retire_locked(self._entries.pop(k)[1], now)
                self.churn += 1
            to_close = self._reap_locked(now)
            hit = None
            entry = self._entries.get(key)
            if entry is not None:
                old_fp, channel, _ = entry
                if old_fp == fingerprint:
                    self._entries[key] = (old_fp, channel, now)
                    hit = channel
                else:
                    self._retire_locked(channel, now)
                    del self._entries[key]
                    self.churn += 1
        # Reaped channels must close even if dial() below raises — they
        # are already off the retired list, so this is their only close.
        try:
            if hit is not None:
                return hit
            # Dial outside the lock (it can block on resolution).
            channel = dial()
            with self._lock:
                raced = self._entries.get(key)
                if raced is not None and raced[0] == fingerprint:
                    # Another thread dialed with the same material
                    # concurrently; keep theirs.
                    channel.close()
                    self._entries[key] = (raced[0], raced[1], now)
                    channel = raced[1]
                else:
                    if raced is not None:
                        # The racing dial used different (e.g.
                        # pre-rotation) material; ours is what the
                        # caller just loaded — it wins.
                        self._retire_locked(raced[1], now)
                    self._entries[key] = (fingerprint, channel, now)
            return channel
        finally:
            for ch in to_close:
                ch.close()

    def invalidate(self, key: Hashable) -> None:
        """Drop ``key`` so the next acquire re-dials.  The old channel is
        retired (closed after the grace), not cancelled out from under
        concurrent calls.  Ripe retirees are also reaped here, so traffic
        stopping after an invalidation cannot strand sockets until some
        future get()."""
        now = time.monotonic()
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._retire_locked(entry[1], now)
                self.churn += 1
            to_close = self._reap_locked(now)
        for channel in to_close:
            channel.close()

    def close(self) -> None:
        """Immediate close of everything — process/driver shutdown."""
        with self._lock:
            channels = [ch for _, ch, _ in self._entries.values()]
            channels += [ch for ch, _ in self._retired]
            self._entries.clear()
            self._retired.clear()
        for channel in channels:
            channel.close()
