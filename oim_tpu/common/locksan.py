"""Runtime lock-order sanitizer (concvet's dynamic half, ISSUE 19).

The static ``lock-order`` pass proves what it can see: ``with``-nesting
and one level of intra-class calls.  Cross-class call chains, callback
hops, and composition it cannot resolve are exactly where ordering bugs
hide — so the serve plane's locks are constructed through the factories
below, and when ``OIM_LOCK_SANITIZER=1`` is set (the chaos/migrate/qos
suites set it) every acquisition is checked against a process-global
order table:

- each thread keeps a stack of the sanitized locks it holds;
- acquiring B while holding A records the directed edge ``A → B`` with
  the acquiring stack as its witness (first observation wins);
- acquiring B while holding A when ``B → A`` was ever observed — on ANY
  thread, at ANY earlier time — raises :class:`LockOrderInversion`
  BEFORE blocking on the acquire, with both witness stacks attached.
  A potential deadlock becomes a deterministic, debuggable exception
  even when the two threads never actually interleave fatally.

Unset (production default), the factories return the raw ``threading``
objects: zero wrapper, zero per-acquire work, nothing allocated beyond
the lock itself.  Same-name edges are never recorded (RLock
re-entrancy; a Condition re-acquiring its own lock after ``wait``).

The factories are first-class lock constructors to the static passes
too: ``tools/oimlint`` (lock-discipline, lock-order, atomicity) treats
``locksan.new_lock/new_rlock/new_condition`` exactly like the
``threading`` ctors, so adopting the sanitizer never blinds the
analyzer.
"""

from __future__ import annotations

import os
import threading
import traceback


def enabled() -> bool:
    """True when the sanitizer env switch is set (checked at factory
    call time, so a test can flip it before constructing an engine)."""
    return os.environ.get("OIM_LOCK_SANITIZER", "") not in ("", "0")


class LockOrderInversion(RuntimeError):
    """Acquisition order contradicts a previously witnessed order."""


# -- process-global order table ---------------------------------------------

# (first_name, then_name) -> witness stack of the edge's first sighting.
_order: dict[tuple[str, str], str] = {}
_order_lock = threading.Lock()
_tls = threading.local()


def _held() -> list[str]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack() -> str:
    # Drop the two sanitizer frames; keep the acquiring call chain.
    return "".join(traceback.format_stack(limit=18)[:-2])


def _check_and_note(name: str) -> None:
    """Record edges held → ``name``; raise on a witnessed inversion.

    Runs BEFORE the real acquire so an inversion surfaces as an
    exception at the second acquisition site, never as a hang."""
    held = _held()
    if not held:
        return
    stack = None
    for prior in held:
        if prior == name:
            continue  # re-entrant acquisition of the same lock
        with _order_lock:
            inverse = _order.get((name, prior))
            if inverse is not None:
                raise LockOrderInversion(
                    f"lock-order inversion: acquiring {name!r} while "
                    f"holding {prior!r}, but the opposite order "
                    f"({name!r} before {prior!r}) was witnessed "
                    f"earlier.\n--- earlier witness ({name!r} -> "
                    f"{prior!r}) ---\n{inverse}--- this acquisition "
                    f"({prior!r} -> {name!r}) ---\n{stack or _stack()}"
                )
            if (prior, name) not in _order:
                if stack is None:
                    stack = _stack()
                _order[(prior, name)] = stack


def _push(name: str) -> None:
    _held().append(name)


def _pop(name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


def reset() -> None:
    """Clear the global order table (test isolation)."""
    with _order_lock:
        _order.clear()


def order_table() -> dict[tuple[str, str], str]:
    """Snapshot of the witnessed edges (observability/tests)."""
    with _order_lock:
        return dict(_order)


# -- wrappers ----------------------------------------------------------------


class _SanLock:
    """Order-checking proxy over a ``threading`` lock primitive."""

    __slots__ = ("name", "_raw")

    def __init__(self, name: str, raw):
        self.name = name
        self._raw = raw

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _check_and_note(self.name)
        got = self._raw.acquire(blocking, timeout)
        if got:
            _push(self.name)
        return got

    def release(self) -> None:
        self._raw.release()
        _pop(self.name)

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<locksan {type(self).__name__} {self.name!r}>"


class _SanRLock(_SanLock):
    __slots__ = ()

    def locked(self) -> bool:  # RLock has no locked() pre-3.12
        raise AttributeError("locked() is not part of the RLock surface")


class _SanCondition(_SanLock):
    """Condition with the order discipline on its underlying lock.

    ``wait`` releases the lock for the duration: the held-stack entry
    is popped before blocking and re-pushed after (the re-acquire on
    wake repeats an already-witnessed order, so it is not re-checked —
    checking it would misfire against locks taken after the original
    acquisition)."""

    __slots__ = ()

    def wait(self, timeout: float | None = None) -> bool:
        _pop(self.name)
        try:
            return self._raw.wait(timeout)
        finally:
            _push(self.name)

    def wait_for(self, predicate, timeout: float | None = None) -> bool:
        return self._raw.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._raw.notify(n)

    def notify_all(self) -> None:
        self._raw.notify_all()


# -- factories ---------------------------------------------------------------


def new_lock(name: str):
    """A ``threading.Lock`` — raw when the sanitizer is off, an
    order-checking wrapper named ``name`` when on."""
    raw = threading.Lock()
    return _SanLock(name, raw) if enabled() else raw


def new_rlock(name: str):
    """A ``threading.RLock`` — raw or order-checked, like
    :func:`new_lock`."""
    raw = threading.RLock()
    return _SanRLock(name, raw) if enabled() else raw


def new_condition(name: str):
    """A ``threading.Condition`` (own lock) — raw or order-checked."""
    raw = threading.Condition()
    return _SanCondition(name, raw) if enabled() else raw
