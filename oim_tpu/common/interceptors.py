"""gRPC server interceptors: payload logging and peer-CN enforcement.

≙ reference pkg/oim-common/tracing.go:29-148 (``LogGRPCServer`` with pluggable
payload formatters incl. secret stripping) and grpc.go:102-125 (server-side
expected-peer verification).  Handlers run with a context logger tagged with
the gRPC method so nested calls show causality (≙ tracing.go:134-140).
"""

from __future__ import annotations

import contextlib
from typing import Callable

import grpc

from oim_tpu import log
from oim_tpu.common.tlsconfig import peer_common_name

# ---------------------------------------------------------------------------
# Payload formatters (≙ CompletePayloadFormatter / StripSecretsFormatter)

_SECRET_FIELD_NAMES = ("secret", "passphrase", "password", "credential")


def _is_secret_field(name: str) -> bool:
    lowered = name.lower()
    return any(s in lowered for s in _SECRET_FIELD_NAMES)


def complete_formatter(msg) -> str:
    """Log the full payload."""
    try:
        return _format_msg(msg, strip=False)
    except Exception:
        return repr(msg)


def strip_secrets_formatter(msg) -> str:
    """Log payloads with secret-ish fields redacted (≙ protosanitizer use)."""
    try:
        return _format_msg(msg, strip=True)
    except Exception:
        return f"<{type(msg).__name__}>"


def null_formatter(msg) -> str:
    return f"<{type(msg).__name__}>"


def _format_msg(msg, strip: bool) -> str:
    if not hasattr(msg, "DESCRIPTOR"):
        return repr(msg)
    parts = []
    for fd, value in msg.ListFields():
        if strip and _is_secret_field(fd.name):
            parts.append(f"{fd.name}=***stripped***")
        elif fd.type == fd.TYPE_MESSAGE:
            # protobuf >=6 exposes is_repeated as a property; older runtimes
            # as a method or only as the deprecated .label.
            rep = getattr(fd, "is_repeated", None)
            if rep is None:
                rep = fd.label == fd.LABEL_REPEATED
            elif callable(rep):
                rep = rep()
            if rep:
                parts.append(
                    f"{fd.name}=[{', '.join(_format_msg(v, strip) for v in value)}]"
                )
            else:
                parts.append(f"{fd.name}={_format_msg(value, strip)}")
        else:
            parts.append(f"{fd.name}={value!r}")
    return f"{type(msg).__name__}({', '.join(parts)})"


# ---------------------------------------------------------------------------
# Server interceptors.
#
# grpc-python interceptors only see call details, not the ServicerContext, so
# both logging and peer checks wrap the *behavior* function where the context
# (and thus the TLS auth info) is available.


def _wrap_handler(handler: grpc.RpcMethodHandler, wrap: Callable):
    if handler is None:
        return None
    if handler.unary_unary:
        return grpc.unary_unary_rpc_method_handler(
            wrap(handler.unary_unary),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
    if handler.unary_stream:
        return grpc.unary_stream_rpc_method_handler(
            wrap(handler.unary_stream),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
    if handler.stream_unary:
        return grpc.stream_unary_rpc_method_handler(
            wrap(handler.stream_unary),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
    return grpc.stream_stream_rpc_method_handler(
        wrap(handler.stream_stream),
        request_deserializer=handler.request_deserializer,
        response_serializer=handler.response_serializer,
    )


class ObservingServerInterceptor(grpc.ServerInterceptor):
    """Shared scaffold for behavior-wrapping server interceptors.

    grpc-python interceptors never see the ServicerContext, so logging,
    tracing, and metrics all need the same plumbing: fetch the handler,
    split unary- vs stream-response, wrap the behavior, and rebuild the
    handler with its serializers (``_wrap_handler``).  Subclasses supply
    only their observation as a context manager: ``observe`` runs around
    the handler (including the full drain of a streaming response) and
    may yield a callable that receives the unary response object.
    """

    def observe(self, method, handler_call_details, request_or_iterator, context):
        raise NotImplementedError

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        method = handler_call_details.method
        streams_response = bool(handler.unary_stream or handler.stream_stream)

        def wrap(behavior):
            if streams_response:
                # The behavior returns a generator that gRPC drains
                # *after* the call below returns, so the observation must
                # live for the whole iteration.
                def wrapped_stream(request_or_iterator, context):
                    with self.observe(
                        method, handler_call_details, request_or_iterator, context
                    ):
                        yield from behavior(request_or_iterator, context)

                return wrapped_stream

            def wrapped(request_or_iterator, context):
                with self.observe(
                    method, handler_call_details, request_or_iterator, context
                ) as on_response:
                    response = behavior(request_or_iterator, context)
                    if on_response is not None:
                        on_response(response)
                    return response

            return wrapped

        return _wrap_handler(handler, wrap)


class LogServerInterceptor(ObservingServerInterceptor):
    """Logs every call with the configured payload formatter and binds the
    context logger with the method name for the duration of the handler."""

    def __init__(self, formatter: Callable = strip_secrets_formatter) -> None:
        self.formatter = formatter

    @contextlib.contextmanager
    def observe(self, method, handler_call_details, request_or_iterator, context):
        fmt = self.formatter
        with log.with_fields(method=method):
            logger = log.current()
            if hasattr(request_or_iterator, "DESCRIPTOR"):
                logger.debug("request", payload=fmt(request_or_iterator))
            else:
                logger.debug(
                    "request",
                    payload=f"<{type(request_or_iterator).__name__}>",
                )

            def on_response(response):
                if hasattr(response, "DESCRIPTOR"):
                    logger.debug("response", payload=fmt(response))

            try:
                yield on_response
            except grpc.RpcError:
                raise
            except Exception as exc:
                logger.error("handler failed", error=str(exc))
                raise


class PeerCheckInterceptor(grpc.ServerInterceptor):
    """Rejects calls whose client CN differs from the expected one.

    ≙ the reference's server-side ``VerifyPeerCertificate`` pinning (reference
    pkg/oim-common/grpc.go:102-125): a controller only accepts the registry
    (CN ``component.registry``) as a client.
    """

    def __init__(self, expected_cn: str) -> None:
        self.expected_cn = expected_cn

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or not self.expected_cn:
            return handler
        expected = self.expected_cn

        def wrap(behavior):
            def wrapped(request_or_iterator, context):
                cn = peer_common_name(context)
                if cn != expected:
                    context.abort(
                        grpc.StatusCode.UNAUTHENTICATED,
                        f"expected peer {expected!r}, got {cn!r}",
                    )
                return behavior(request_or_iterator, context)

            return wrapped

        return _wrap_handler(handler, wrap)
