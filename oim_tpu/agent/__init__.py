"""Device-plane client + test double (≙ reference pkg/spdk).

``Client``/``Agent`` talk the NDJSON JSON-RPC protocol of doc/agent-protocol.md
to a tpu-agent daemon (the C++ one under native/tpu-agent, or the in-process
Python ``FakeAgentServer``).
"""

from oim_tpu.agent.client import AgentError, Client, is_agent_error
from oim_tpu.agent.agent import Agent
from oim_tpu.agent.fake import FakeAgentServer, ChipStore

__all__ = [
    "Agent",
    "AgentError",
    "Client",
    "is_agent_error",
    "FakeAgentServer",
    "ChipStore",
]

# errno-style application error codes (doc/agent-protocol.md).
EEXIST = -17
ENODEV = -19
ENOSPC = -28
EBUSY = -16
# JSON-RPC: method not served (how a health-oblivious daemon answers
# get_health; the HealthReporter degrades to get_chips on it).
METHOD_NOT_FOUND = -32601
