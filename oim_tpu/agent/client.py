"""JSON-RPC 2.0 NDJSON client for the tpu-agent socket.

≙ reference pkg/spdk/client.go: a small line-oriented JSON-RPC client over a
Unix stream socket with full wire logging (client.go:230-262) and errors
surfaced as typed exceptions matchable by code (≙ ``IsJSONError``,
client.go:70-85).

Transport resilience (oim_tpu.common.resilience): a broken socket no
longer poisons the client forever — EPIPE/ECONNRESET/EOF during a call
drops the connection and re-dials under the shared RetryPolicy, so an
agent daemon restart costs the caller one backoff, not a new Client.
Request ids stay monotonically increasing across reconnects (every
attempt takes a fresh id), so a stale response line can never be matched
to a newer request.  Application errors (AgentError) are the daemon's
*answer* and are never retried.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Any

from oim_tpu import log
from oim_tpu.common import events, resilience, tracing


class AgentError(Exception):
    """A JSON-RPC error response: ``code: %d msg: %s``."""

    def __init__(self, code: int, message: str):
        super().__init__(f"code: {code} msg: {message}")
        self.code = code
        self.message = message


def is_agent_error(exc: BaseException, code: int) -> bool:
    return isinstance(exc, AgentError) and exc.code == code


class Client:
    """One connection to a tpu-agent socket; thread-safe request/response."""

    def __init__(
        self,
        path: str,
        timeout: float = 60.0,
        retry: resilience.RetryPolicy | None = None,
    ) -> None:
        self.path = path
        self.timeout = timeout
        self.retry = retry if retry is not None else resilience.RetryPolicy.from_env()
        self._lock = threading.Lock()
        self._next_id = 0
        self._closed = False
        self._sock: socket.socket | None = None
        self._file = None
        # Connect eagerly so a missing/unserved socket fails in the
        # caller's face (LocalBackend maps the OSError to UNAVAILABLE).
        self._connect()

    def _connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.settimeout(self.timeout)
            sock.connect(self.path)
            file = sock.makefile("rb")
        except BaseException:
            # A failed connect must not leak the half-built socket.
            sock.close()
            raise
        self._sock = sock
        self._file = file

    def _drop_connection(self) -> None:
        """Close the (possibly dead) transport; caller holds the lock.
        The next attempt re-dials."""
        file, sock = self._file, self._sock
        self._file = None
        self._sock = None
        for closable in (file, sock):
            if closable is not None:
                try:
                    closable.close()
                except OSError:
                    pass

    def invoke(self, method: str, params: dict[str, Any] | None = None) -> Any:
        # The device-plane hop gets its own span (the JSON-RPC protocol
        # itself stays unchanged — the C++ agent is trace-oblivious, like
        # SPDK was to the reference's planned Jaeger spans).
        with tracing.start_span(f"agent/{method}", transport="jsonrpc"):
            return self._invoke(method, params)

    def _invoke(self, method: str, params: dict[str, Any] | None = None) -> Any:
        # The lock spans ONE roundtrip, not the whole ladder: pairing on
        # the stream stays atomic, but a failing call's backoff sleeps
        # must not serialize every other thread behind it.  Every failure
        # path in _roundtrip drops the connection before raising, so the
        # next attempt (any thread's) starts from a fresh dial;
        # retryable_dial additionally treats a missing socket file
        # (daemon mid-restart) as a hop failure.
        def one_attempt(attempt):
            with self._lock:
                return self._roundtrip(method, params, attempt.timeout)

        def on_retry(exc: BaseException, attempt: int) -> None:
            # Flight-recorder breadcrumb: every re-dial of the device
            # plane is a state transition worth a timeline row (a daemon
            # restart shows up as a burst of these, trace-linked to the
            # RPC that rode through it).
            events.emit(
                "agent.reconnect",
                component="agent-client",
                severity=events.WARNING,
                subject=self.path,
                method=method,
                attempt=attempt,
                error=str(exc),
            )

        response = resilience.call_with_retry(
            one_attempt,
            self.retry,
            component="agent-client",
            op=method,
            classify=resilience.retryable_dial,
            on_retry=on_retry,
        )
        if "error" in response:
            err = response["error"]
            raise AgentError(int(err.get("code", 0)), str(err.get("message", "")))
        return response.get("result")

    def _roundtrip(
        self,
        method: str,
        params: dict[str, Any] | None,
        budget: float | None = None,
    ):
        """One attempt: (re)connect if needed, send, read the reply line.
        Raises ConnectionError/OSError on transport breaks — the
        retryable class — after dropping the connection, so the next
        attempt starts from a fresh dial.  ``budget`` (the retry ladder's
        remaining overall deadline, if any) tightens the socket timeout
        so a HANGING daemon cannot stall one attempt past it."""
        if self._closed:
            # Latched: a closed client must not silently resurrect its
            # connection (nobody would ever close the new socket).
            raise RuntimeError(f"agent client for {self.path} is closed")
        if self._sock is None:
            self._connect()
        self._sock.settimeout(
            self.timeout if budget is None
            else min(self.timeout, max(budget, 0.05))
        )
        self._next_id += 1
        request: dict[str, Any] = {
            "jsonrpc": "2.0",
            "id": self._next_id,
            "method": method,
        }
        # params omitted when empty (≙ reference client.go:104-126).
        if params:
            request["params"] = params
        wire = json.dumps(request, separators=(",", ":")) + "\n"
        logger = log.current()
        logger.debug("agent request", data=wire.rstrip())
        try:
            self._sock.sendall(wire.encode())
            line = self._file.readline()
        except OSError:
            self._drop_connection()
            raise
        if not line:
            self._drop_connection()
            raise ConnectionError(f"agent at {self.path} closed connection")
        logger.debug("agent response", data=line.decode().rstrip())
        try:
            response = json.loads(line)
        except ValueError as exc:
            # A torn mid-line write from a dying daemon is a transport
            # break, not an answer.
            self._drop_connection()
            raise ConnectionError(
                f"agent at {self.path} sent unparseable frame: {exc}"
            ) from exc
        if response.get("id") != request["id"]:
            self._drop_connection()
            raise ConnectionError(
                f"agent response id {response.get('id')} != {request['id']}"
            )
        return response

    def close(self) -> None:
        """Idempotent; safe on a client whose connect failed.  Latches:
        later invokes fail with RuntimeError instead of reconnecting."""
        with self._lock:
            self._closed = True
            self._drop_connection()

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
