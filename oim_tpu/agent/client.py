"""JSON-RPC 2.0 NDJSON client for the tpu-agent socket.

≙ reference pkg/spdk/client.go: a small line-oriented JSON-RPC client over a
Unix stream socket with full wire logging (client.go:230-262) and errors
surfaced as typed exceptions matchable by code (≙ ``IsJSONError``,
client.go:70-85).  Deliberately standalone: depends only on oim_tpu.log.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Any

from oim_tpu import log
from oim_tpu.common import tracing


class AgentError(Exception):
    """A JSON-RPC error response: ``code: %d msg: %s``."""

    def __init__(self, code: int, message: str):
        super().__init__(f"code: {code} msg: {message}")
        self.code = code
        self.message = message


def is_agent_error(exc: BaseException, code: int) -> bool:
    return isinstance(exc, AgentError) and exc.code == code


class Client:
    """One connection to a tpu-agent socket; thread-safe request/response."""

    def __init__(self, path: str, timeout: float = 60.0) -> None:
        self.path = path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(path)
        self._file = self._sock.makefile("rb")
        self._lock = threading.Lock()
        self._next_id = 0

    def invoke(self, method: str, params: dict[str, Any] | None = None) -> Any:
        # The device-plane hop gets its own span (the JSON-RPC protocol
        # itself stays unchanged — the C++ agent is trace-oblivious, like
        # SPDK was to the reference's planned Jaeger spans).
        with tracing.start_span(f"agent/{method}", transport="jsonrpc"):
            return self._invoke(method, params)

    def _invoke(self, method: str, params: dict[str, Any] | None = None) -> Any:
        with self._lock:
            self._next_id += 1
            request: dict[str, Any] = {
                "jsonrpc": "2.0",
                "id": self._next_id,
                "method": method,
            }
            # params omitted when empty (≙ reference client.go:104-126).
            if params:
                request["params"] = params
            wire = json.dumps(request, separators=(",", ":")) + "\n"
            logger = log.current()
            logger.debug("agent request", data=wire.rstrip())
            self._sock.sendall(wire.encode())
            line = self._file.readline()
            if not line:
                raise ConnectionError(f"agent at {self.path} closed connection")
            logger.debug("agent response", data=line.decode().rstrip())
            response = json.loads(line)
        if response.get("id") != request["id"]:
            raise ConnectionError(
                f"agent response id {response.get('id')} != {request['id']}"
            )
        if "error" in response:
            err = response["error"]
            raise AgentError(int(err.get("code", 0)), str(err.get("message", "")))
        return response.get("result")

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            self._sock.close()

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
