"""In-process fake tpu-agent: chip store + NDJSON JSON-RPC server.

The Python reference implementation of doc/agent-protocol.md, serving the
same role as the reference's Malloc BDev mode (volatile fake devices that let
every layer above run without hardware, reference spec.md:119-122).  The C++
daemon under native/tpu-agent implements the identical semantics; the shared
suite in tests/test_agent_protocol.py holds both to this file's behavior.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import socket
import socketserver
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from oim_tpu import log

EEXIST = -17
ENODEV = -19
ENOSPC = -28
EBUSY = -16
INVALID_PARAMS = -32602
METHOD_NOT_FOUND = -32601
PARSE_ERROR = -32700
INVALID_REQUEST = -32600

COORDINATOR_PORT_BASE = 8476


class RpcAppError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


# Default JSON-RPC error code for injected ``chaos_error`` faults
# (server-defined range -32000..-32099, doc/agent-protocol.md).
CHAOS_ERROR_CODE = -32050


@dataclass
class ChaosConfig:
    """Transport-fault injection state (doc/agent-protocol.md, chaos_*).

    Armed via ``inject_fault`` with a ``chaos_*`` kind; every subsequent
    request (except ``inject_fault`` itself — the healing path must stay
    reachable) rolls ``rng.random() < rate`` and, on a hit, suffers:

    - ``drop``: the connection is severed WITHOUT executing the request
      (the client sees EOF; the operation never happened),
    - ``disconnect``: the request IS executed, then the connection is
      severed before the reply — the ambiguous "executed, reply lost"
      window that makes idempotency keys load-bearing,
    - ``delay``: the reply is held for ``delay_s`` (deadline pressure),
    - ``error``: a JSON-RPC error with ``error_code`` is returned.

    Seeded RNG: the same (seed, request sequence) always faults the same
    calls, so soak failures replay deterministically.
    """

    mode: str = ""
    rate: float = 1.0
    delay_s: float = 0.05
    error_code: int = CHAOS_ERROR_CODE
    methods: frozenset[str] | None = None  # None = every method
    count: int = 0  # > 0: disarm after this many hits (exact-N scripting)
    rng: random.Random = field(default_factory=random.Random)


@dataclass
class Chip:
    chip_id: int
    device_path: str
    pci: str
    accel_type: str
    phys_coord: tuple[int, ...]
    allocation: str = ""
    # Device health telemetry (oim_tpu/health): OK / DEGRADED / FAILED plus
    # a cumulative ICI-link error counter.  Not part of the chip's wire
    # shape (to_json) — health travels through get_health only, so the
    # shared protocol suite's chip-object assertions hold for both
    # implementations unchanged.
    health: str = "OK"
    ici_link_errors: int = 0

    def to_json(self, coord: tuple[int, ...] | None = None) -> dict[str, Any]:
        out: dict[str, Any] = {
            "chip_id": self.chip_id,
            "device_path": self.device_path,
            "pci": self.pci,
            "accel_type": self.accel_type,
            "phys_coord": list(self.phys_coord),
            "allocation": self.allocation,
        }
        if coord is not None:
            out["coord"] = list(coord)
        return out


@dataclass
class Allocation:
    name: str
    chip_ids: list[int]
    mesh: tuple[int, ...]
    attached: bool = False
    provisioned: bool = False
    coordinator_port: int = 0
    # chip_id -> coordinate within mesh
    coords: dict[int, tuple[int, ...]] = field(default_factory=dict)


def _sub_boxes(n: int, dims: tuple[int, ...]) -> list[tuple[int, ...]]:
    """All box shapes with product n fitting inside dims, most compact first.

    Deterministic: sorted by (longest edge, perimeter, shape) so the same
    request always yields the same placement — the TPU analog of the
    reference's deterministic SCSI target scan order (reference
    pkg/oim-controller/controller.go:131-148), except compactness-aware so
    collectives stay on short ICI paths.
    """
    shapes = set()

    def rec(prefix: tuple[int, ...], remaining: int, axis: int) -> None:
        if axis == len(dims):
            if remaining == 1:
                shapes.add(prefix)
            return
        for d in range(1, min(dims[axis], remaining) + 1):
            if remaining % d == 0:
                rec(prefix + (d,), remaining // d, axis + 1)

    rec((), n, 0)
    return sorted(shapes, key=lambda s: (max(s), sum(s), s))


class ChipStore:
    """Chip inventory + allocations; the one mutex-guarded source of truth
    (the role SPDK's bdev/vhost tables play)."""

    def __init__(
        self,
        mesh: tuple[int, ...],
        accel_type: str = "v5p",
        device_dir: str | None = None,
        device_paths: list[str] | None = None,
        pjrt_version: str = "",
    ) -> None:
        self.mesh = tuple(int(d) for d in mesh)
        self.accel_type = accel_type
        self.pjrt_version = pjrt_version
        self._lock = threading.Lock()
        self.allocations: dict[str, Allocation] = {}
        count = 1
        for d in self.mesh:
            count *= d
        coords = list(itertools.product(*[range(d) for d in self.mesh]))
        self.chips: dict[int, Chip] = {}
        for i in range(count):
            if device_paths is not None:
                path = device_paths[i]
            elif device_dir is not None:
                os.makedirs(device_dir, exist_ok=True)
                path = os.path.join(device_dir, f"accel{i}")
                # Stub device file: NodeStage later bind-mounts/symlinks it
                # into the pod, so it must exist on disk in fake mode.
                with open(path, "w") as f:
                    f.write(f"fake-tpu-chip {i}\n")
            else:
                path = f"/dev/accel{i}"
            self.chips[i] = Chip(
                chip_id=i,
                device_path=path,
                pci=f"0000:{i:02x}:05.0",
                accel_type=accel_type,
                phys_coord=coords[i],
            )
        self._coord_to_id = {c.phys_coord: c.chip_id for c in self.chips.values()}
        # Scripted faults: (calls_remaining, chip_id, kind).  Each
        # get_health decrements every pending counter and applies the
        # faults that reach zero — deterministic ("the Nth scrape sees the
        # failure"), no wall clock involved.
        self._pending_faults: list[list] = []
        # Transport chaos (chaos_* inject_fault kinds): None = healthy.
        self._chaos: ChaosConfig | None = None

    # -- health ------------------------------------------------------------

    _FAULT_KINDS = ("degraded", "failed", "link_errors", "clear")

    def inject_fault(
        self, chip_id: int, kind: str, after_n_calls: int = 0
    ) -> dict[str, Any]:
        """Schedule a deterministic fault on one chip.

        ``kind``: ``failed``/``degraded`` set the health state,
        ``link_errors`` bumps the ICI error counter, ``clear`` restores the
        chip to pristine OK.  With ``after_n_calls`` > 0 the fault
        manifests only after that many subsequent ``get_health`` calls, so
        tests can script "the reporter's Nth scrape sees it"."""
        if kind not in self._FAULT_KINDS:
            raise RpcAppError(
                INVALID_PARAMS,
                f"kind must be one of {'/'.join(self._FAULT_KINDS)}",
            )
        with self._lock:
            chip = self.chips.get(int(chip_id))
            if chip is None:
                raise RpcAppError(ENODEV, f"no chip {chip_id}")
            if after_n_calls > 0:
                self._pending_faults.append([int(after_n_calls), chip.chip_id, kind])
            else:
                self._apply_fault(chip, kind)
            return {"chip_id": chip.chip_id, "health": chip.health,
                    "pending": after_n_calls > 0}

    def _apply_fault(self, chip: Chip, kind: str) -> None:
        """Mutate chip health; caller holds the lock."""
        if kind == "failed":
            chip.health = "FAILED"
        elif kind == "degraded":
            # A FAILED chip never un-fails by a mere degradation report.
            if chip.health != "FAILED":
                chip.health = "DEGRADED"
        elif kind == "link_errors":
            chip.ici_link_errors += 1
        elif kind == "clear":
            chip.health = "OK"
            chip.ici_link_errors = 0
            self._pending_faults = [
                p for p in self._pending_faults if p[1] != chip.chip_id
            ]

    # -- transport chaos ---------------------------------------------------

    _CHAOS_KINDS = (
        "chaos_drop", "chaos_delay", "chaos_error", "chaos_disconnect",
        "chaos_clear",
    )

    def inject_chaos(self, kind: str, params: dict[str, Any]) -> dict[str, Any]:
        """Arm (or clear) transport-fault injection; see ChaosConfig."""
        if kind not in self._CHAOS_KINDS:
            raise RpcAppError(
                INVALID_PARAMS,
                f"kind must be one of {'/'.join(self._CHAOS_KINDS)}",
            )
        with self._lock:
            if kind == "chaos_clear":
                self._chaos = None
                return {"chaos": ""}
            try:
                rate = float(params.get("rate", 1.0))
                delay_s = float(params.get("delay_s", 0.05))
                error_code = int(params.get("error_code", CHAOS_ERROR_CODE))
                count = int(params.get("count", 0))
                rng = (
                    random.Random(params["seed"]) if "seed" in params
                    else random.Random()
                )
            except (TypeError, ValueError):
                # A bad knob must get a JSON-RPC answer, not a severed
                # connection indistinguishable from armed chaos.
                raise RpcAppError(
                    INVALID_PARAMS,
                    "rate/delay_s must be floats, error_code/count ints, "
                    "seed hashable",
                ) from None
            if not 0.0 <= rate <= 1.0:
                raise RpcAppError(INVALID_PARAMS, f"rate {rate} not in [0, 1]")
            methods = params.get("methods")
            self._chaos = ChaosConfig(
                mode=kind[len("chaos_"):],
                rate=rate,
                delay_s=delay_s,
                error_code=error_code,
                methods=frozenset(methods) if methods else None,
                count=count,
                rng=rng,
            )
            return {"chaos": self._chaos.mode, "rate": rate}

    def chaos_action(self, method: str) -> ChaosConfig | None:
        """Roll the dice for one request; the armed config on a hit.
        ``inject_fault`` is exempt so tests can always heal the agent."""
        with self._lock:
            cfg = self._chaos
            if cfg is None or method == "inject_fault":
                return None
            if cfg.methods is not None and method not in cfg.methods:
                return None
            if cfg.rng.random() >= cfg.rate:
                return None
            if cfg.count > 0:
                cfg.count -= 1
                if cfg.count == 0:
                    self._chaos = None  # budget spent: healthy again
            return cfg

    def get_health(self) -> list[dict[str, Any]]:
        """Per-chip health snapshot; applies any due scripted faults."""
        with self._lock:
            due = []
            for pending in self._pending_faults:
                pending[0] -= 1
                if pending[0] <= 0:
                    due.append(pending)
            self._pending_faults = [
                p for p in self._pending_faults if p not in due
            ]
            for _, chip_id, kind in due:
                chip = self.chips.get(chip_id)
                if chip is not None:
                    self._apply_fault(chip, kind)
            return [
                {
                    "chip_id": c.chip_id,
                    "health": c.health,
                    "ici_link_errors": c.ici_link_errors,
                    "allocation": c.allocation,
                }
                for c in self.chips.values()
            ]

    # -- allocator ---------------------------------------------------------

    def _find_chips(
        self, n: int, topology: tuple[int, ...] | None
    ) -> tuple[list[int], tuple[int, ...]]:
        """Pick n free chips; returns (chip_ids in mesh order, mesh shape)."""
        free = {cid for cid, c in self.chips.items() if not c.allocation}
        if n > len(free):
            raise RpcAppError(ENOSPC, f"need {n} chips, {len(free)} free")
        if topology:
            # TPU topology convention (mirrors chip_store.cc): a
            # lower-rank request is trailing-1-padded — "2x2" on a
            # 2x2x1 host means 2x2x1 (the gke-tpu dialect writes 2D
            # topologies against 3D host meshes).
            padded = tuple(topology) + (1,) * (len(self.mesh) - len(topology))
            shapes = [padded]
        else:
            shapes = _sub_boxes(n, self.mesh) or []
        for shape in shapes:
            if len(shape) != len(self.mesh):
                continue
            # Slide the box over every origin, deterministic order.
            origins = itertools.product(
                *[range(self.mesh[a] - shape[a] + 1) for a in range(len(shape))]
            )
            for origin in origins:
                ids = []
                for offset in itertools.product(*[range(d) for d in shape]):
                    coord = tuple(o + d for o, d in zip(origin, offset))
                    cid = self._coord_to_id[coord]
                    if cid not in free:
                        break
                    ids.append(cid)
                else:
                    return ids, tuple(shape)
        if topology:
            raise RpcAppError(
                ENOSPC, f"no free {'x'.join(map(str, topology))} sub-mesh"
            )
        # Fragmented: fall back to a linear mesh of arbitrary free chips.
        ids = sorted(free)[:n]
        return ids, (n,)

    # -- RPC semantics -----------------------------------------------------

    def create_allocation(
        self,
        name: str,
        chip_count: int,
        topology: list[int] | None = None,
        provisioned: bool = False,
    ) -> Allocation:
        if not name or chip_count <= 0:
            raise RpcAppError(INVALID_PARAMS, "name and chip_count>0 required")
        topo = tuple(int(d) for d in topology) if topology else None
        if topo:
            prod = 1
            for d in topo:
                prod *= d
            if prod != chip_count:
                raise RpcAppError(
                    INVALID_PARAMS,
                    f"topology {list(topo)} does not multiply to {chip_count}",
                )
        with self._lock:
            existing = self.allocations.get(name)
            if existing is not None:
                if len(existing.chip_ids) != chip_count:
                    raise RpcAppError(
                        EEXIST,
                        f"allocation {name!r} exists with "
                        f"{len(existing.chip_ids)} chips",
                    )
                return existing
            ids, mesh = self._find_chips(chip_count, topo)
            coords = {
                cid: offset
                for cid, offset in zip(
                    ids, itertools.product(*[range(d) for d in mesh])
                )
            }
            alloc = Allocation(
                name=name,
                chip_ids=ids,
                mesh=mesh,
                coords=coords,
                provisioned=provisioned,
            )
            for cid in ids:
                self.chips[cid].allocation = name
            self.allocations[name] = alloc
            return alloc

    def delete_allocation(self, name: str) -> None:
        with self._lock:
            alloc = self.allocations.get(name)
            if alloc is None:
                raise RpcAppError(ENODEV, f"no allocation {name!r}")
            if alloc.attached:
                raise RpcAppError(EBUSY, f"allocation {name!r} is attached")
            for cid in alloc.chip_ids:
                self.chips[cid].allocation = ""
            del self.allocations[name]

    def attach_allocation(self, name: str) -> Allocation:
        with self._lock:
            alloc = self.allocations.get(name)
            if alloc is None:
                raise RpcAppError(ENODEV, f"no allocation {name!r}")
            if not alloc.attached:
                used = {
                    a.coordinator_port
                    for a in self.allocations.values()
                    if a.attached
                }
                port = COORDINATOR_PORT_BASE
                while port in used:
                    port += 1
                alloc.coordinator_port = port
                alloc.attached = True
            return alloc

    def detach_allocation(self, name: str) -> None:
        with self._lock:
            alloc = self.allocations.get(name)
            if alloc is None:
                raise RpcAppError(ENODEV, f"no allocation {name!r}")
            alloc.attached = False
            alloc.coordinator_port = 0

    # -- JSON views --------------------------------------------------------

    def alloc_json(self, alloc: Allocation) -> dict[str, Any]:
        return {
            "name": alloc.name,
            "chip_count": len(alloc.chip_ids),
            "mesh": list(alloc.mesh),
            "attached": alloc.attached,
            "provisioned": alloc.provisioned,
            "coordinator_port": alloc.coordinator_port,
            "chips": [
                self.chips[cid].to_json(coord=alloc.coords[cid])
                for cid in alloc.chip_ids
            ],
        }

    def handle(self, method: str, params: dict[str, Any]) -> Any:
        if method == "get_topology":
            with self._lock:
                free = sum(1 for c in self.chips.values() if not c.allocation)
            out = {
                "accel_type": self.accel_type,
                "mesh": list(self.mesh),
                "chip_count": len(self.chips),
                "free_chips": free,
            }
            if self.pjrt_version:
                out["pjrt_version"] = self.pjrt_version
            return out
        if method == "get_pjrt_info":
            # The Python fake never loads a real plugin; report the version
            # stub when configured so both implementations serve the method
            # (contents are implementation-specific, doc/agent-protocol.md).
            if self.pjrt_version:
                return {"plugin_path": "", "fake": True,
                        "pjrt_version": self.pjrt_version}
            return {}
        if method == "get_chips":
            with self._lock:
                return [c.to_json() for c in self.chips.values()]
        if method == "get_health":
            return self.get_health()
        if method == "inject_fault":
            kind = str(params.get("kind", ""))
            if kind.startswith("chaos_"):
                # Transport chaos is store-wide; no chip_id involved.
                return self.inject_chaos(kind, params)
            if "chip_id" not in params:
                raise RpcAppError(INVALID_PARAMS, "chip_id required")
            return self.inject_fault(
                int(params["chip_id"]),
                kind,
                int(params.get("after_n_calls", 0)),
            )
        if method == "get_allocations":
            name = params.get("name")
            with self._lock:
                if name:
                    alloc = self.allocations.get(name)
                    return [self.alloc_json(alloc)] if alloc else []
                return [
                    self.alloc_json(a)
                    for _, a in sorted(self.allocations.items())
                ]
        if method == "create_allocation":
            alloc = self.create_allocation(
                params.get("name", ""),
                int(params.get("chip_count", 0)),
                params.get("topology"),
                provisioned=bool(params.get("provisioned", False)),
            )
            return self.alloc_json(alloc)
        if method == "delete_allocation":
            self._require_name(params)
            self.delete_allocation(params["name"])
            return True
        if method == "attach_allocation":
            self._require_name(params)
            return self.alloc_json(self.attach_allocation(params["name"]))
        if method == "detach_allocation":
            self._require_name(params)
            self.detach_allocation(params["name"])
            return True
        raise RpcAppError(METHOD_NOT_FOUND, f"method {method!r} not found")

    @staticmethod
    def _require_name(params: dict[str, Any]) -> None:
        if not params.get("name"):
            raise RpcAppError(INVALID_PARAMS, "name required")


class FakeAgentServer:
    """Threaded Unix-socket NDJSON server around a ChipStore."""

    def __init__(self, store: ChipStore, socket_path: str) -> None:
        self.store = store
        self.socket_path = socket_path
        store_ref = store
        live_connections: set = set()
        conn_lock = threading.Lock()
        self._live_connections = live_connections
        self._conn_lock = conn_lock

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                try:
                    while True:
                        line = self.rfile.readline()
                        if not line:
                            return
                        if line == b"\n":
                            # A bare newline is keepalive-benign and
                            # skipped — exactly the C++ daemon's
                            # `if (line.empty()) continue` (whitespace
                            # lines dispatch and get a parse error on
                            # both implementations).
                            continue
                        response, sever = _dispatch_line(store_ref, line)
                        if response is not None:
                            self.wfile.write(
                                (json.dumps(response, separators=(",", ":"))
                                 + "\n").encode()
                            )
                            self.wfile.flush()
                        if sever:
                            # Injected drop/disconnect: kill THIS
                            # connection like a crashing daemon would —
                            # the client's next read sees EOF/RST and its
                            # resilience layer re-dials.
                            try:
                                self.connection.shutdown(socket.SHUT_RDWR)
                            except OSError:
                                pass
                            return
                finally:
                    with conn_lock:
                        live_connections.discard(self.connection)

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

            def process_request(self, request, client_address):
                # Register BEFORE the handler thread spawns (still in the
                # accept loop): stop() snapshotting live_connections can
                # then never miss a just-accepted connection and leave a
                # stale handler serving the old store.
                with conn_lock:
                    live_connections.add(request)
                super().process_request(request, client_address)

        parent = os.path.dirname(socket_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._server = Server(socket_path, Handler)
        self._thread: threading.Thread | None = None

    def start(self) -> "FakeAgentServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="fake-agent"
        )
        self._thread.start()
        log.current().info("fake tpu-agent listening", socket=self.socket_path)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # Join the accept loop: a stop() that returns while serve_forever
        # is still winding down can race a same-socket-path restart
        # (test fixtures do exactly that) into two servers briefly
        # owning one path.  shutdown() has already handshaken, so the
        # join is bounded.
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # Sever established connections too: a crashed daemon takes its
        # connections down with it, and restart-recovery tests rely on
        # clients actually seeing the break (ThreadingMixIn handler
        # threads would otherwise keep serving the OLD store forever).
        with self._conn_lock:
            conns = list(self._live_connections)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)


def _dispatch_line(
    store: ChipStore, line: bytes
) -> tuple[dict[str, Any] | None, bool]:
    """One request → (response-or-None, sever-connection?).

    A ``None`` response with ``sever`` means injected chaos ate the reply
    (drop: before execution; disconnect: after) — the transport break the
    client-side resilience layer exists to absorb.
    """
    req_id = None
    try:
        request = json.loads(line)
        if not isinstance(request, dict):
            raise RpcAppError(INVALID_REQUEST, "not a JSON-RPC 2.0 request")
        req_id = request.get("id")
        if request.get("jsonrpc") != "2.0" or "method" not in request:
            raise RpcAppError(INVALID_REQUEST, "not a JSON-RPC 2.0 request")
        params = request.get("params") or {}
        if not isinstance(params, dict):
            raise RpcAppError(INVALID_PARAMS, "params must be an object")
        method = request["method"]
        chaos = store.chaos_action(method)
        if chaos is not None:
            if chaos.mode == "drop":
                return None, True  # never executed
            if chaos.mode == "error":
                raise RpcAppError(chaos.error_code, "injected chaos error")
            if chaos.mode == "delay":
                time.sleep(chaos.delay_s)
        result = store.handle(method, params)
        if chaos is not None and chaos.mode == "disconnect":
            return None, True  # executed; reply lost — the ambiguous window
        return {"jsonrpc": "2.0", "id": req_id, "result": result}, False
    except RpcAppError as exc:
        return {
            "jsonrpc": "2.0",
            "id": req_id,
            "error": {"code": exc.code, "message": exc.message},
        }, False
    except (json.JSONDecodeError, UnicodeDecodeError, RecursionError) as exc:
        return {
            "jsonrpc": "2.0",
            "id": req_id,
            "error": {"code": PARSE_ERROR, "message": str(exc)},
        }, False
