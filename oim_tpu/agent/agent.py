"""Typed wrappers over the raw JSON-RPC client.

≙ reference pkg/spdk/spdk.go:47-286's per-RPC Args/Response bindings — thin,
validated entry points the controller and CSI local backend call instead of
stringly-typed ``invoke``.
"""

from __future__ import annotations

from typing import Any

from oim_tpu.agent.client import Client


class Agent:
    def __init__(
        self, socket_path: str, timeout: float = 60.0, retry=None
    ) -> None:
        # ``retry`` (a resilience.RetryPolicy) tunes transport-level
        # reconnect/retry; None takes the env-configured default.
        self.client = Client(socket_path, timeout=timeout, retry=retry)

    # -- queries -----------------------------------------------------------

    def get_topology(self) -> dict[str, Any]:
        return self.client.invoke("get_topology")

    def get_chips(self) -> list[dict[str, Any]]:
        return self.client.invoke("get_chips")

    def get_allocations(self, name: str | None = None) -> list[dict[str, Any]]:
        params = {"name": name} if name else None
        return self.client.invoke("get_allocations", params)

    def get_pjrt_info(self) -> dict[str, Any]:
        """Compute-stack report from the daemon's PJRT C-API plugin; ``{}``
        when the daemon was started without one."""
        return self.client.invoke("get_pjrt_info")

    def get_health(self) -> list[dict[str, Any]]:
        """Per-chip health snapshot: ``{chip_id, health, ici_link_errors,
        allocation}`` per chip.  Servers without health telemetry raise
        METHOD_NOT_FOUND (-32601); callers that can degrade should (the
        HealthReporter synthesizes OK states from get_chips then)."""
        return self.client.invoke("get_health")

    def inject_fault(
        self, chip_id: int, kind: str, after_n_calls: int = 0
    ) -> dict[str, Any]:
        """Schedule a deterministic fault (fake/test agents only):
        ``failed``/``degraded``/``link_errors``/``clear``, optionally
        deferred until the Nth subsequent get_health call."""
        params: dict[str, Any] = {"chip_id": chip_id, "kind": kind}
        if after_n_calls:
            params["after_n_calls"] = after_n_calls
        return self.client.invoke("inject_fault", params)

    def inject_chaos(
        self,
        kind: str,
        rate: float = 1.0,
        seed: int | None = None,
        delay_s: float | None = None,
        error_code: int | None = None,
        methods: list[str] | None = None,
        count: int | None = None,
    ) -> dict[str, Any]:
        """Arm transport-fault injection on a fake/test agent:
        ``chaos_drop``/``chaos_delay``/``chaos_error``/``chaos_disconnect``
        afflict a ``rate`` fraction of subsequent requests (seeded RNG for
        reproducibility); ``chaos_clear`` heals.  See
        doc/agent-protocol.md."""
        params: dict[str, Any] = {"kind": kind, "rate": rate}
        if seed is not None:
            params["seed"] = seed
        if delay_s is not None:
            params["delay_s"] = delay_s
        if error_code is not None:
            params["error_code"] = error_code
        if methods is not None:
            params["methods"] = methods
        if count is not None:
            params["count"] = count
        return self.client.invoke("inject_fault", params)

    def find_allocation(self, name: str) -> dict[str, Any] | None:
        found = self.get_allocations(name)
        return found[0] if found else None

    # -- lifecycle ---------------------------------------------------------

    def create_allocation(
        self,
        name: str,
        chip_count: int,
        topology: list[int] | None = None,
        provisioned: bool = False,
    ) -> dict[str, Any]:
        params: dict[str, Any] = {"name": name, "chip_count": chip_count}
        if topology:
            params["topology"] = list(topology)
        if provisioned:
            params["provisioned"] = True
        return self.client.invoke("create_allocation", params)

    def delete_allocation(self, name: str) -> None:
        self.client.invoke("delete_allocation", {"name": name})

    def attach_allocation(self, name: str) -> dict[str, Any]:
        return self.client.invoke("attach_allocation", {"name": name})

    def detach_allocation(self, name: str) -> None:
        self.client.invoke("detach_allocation", {"name": name})

    def close(self) -> None:
        self.client.close()

    def __enter__(self) -> "Agent":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
