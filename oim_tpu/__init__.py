"""oim_tpu — a TPU-native infrastructure-management framework.

A registry → controller → CSI-driver control plane that attaches TPU slices to
Kubernetes pods (capability parity with intel/oim, which attaches SPDK block
devices; see SURVEY.md), plus a JAX/XLA compute path (mesh construction,
DP/TP/SP/PP/EP shardings, ring attention, pallas kernels, a flagship model)
that runs on the provisioned slices.

Layer map (bottom → top), mirroring /root/reference layers 0-8:
  native/tpu-agent      C++ device-plane daemon (≙ SPDK vhost)
  oim_tpu.agent         JSON-RPC client + typed wrappers (≙ pkg/spdk)
  oim_tpu.controller    per-device controller gRPC service (≙ pkg/oim-controller)
  oim_tpu.registry      KV + transparent gRPC proxy (≙ pkg/oim-registry)
  oim_tpu.csi           CSI driver, local/remote backends (≙ pkg/oim-csi-driver)
  oim_tpu.common        shared infra (≙ pkg/oim-common)
  oim_tpu.log           context-carried structured logging (≙ pkg/log)
  oim_tpu.spec          wire spec + generated protobuf bindings (≙ pkg/spec)
  oim_tpu.cli           binaries (≙ cmd/*)
  oim_tpu.parallel/ops/models   the JAX compute path running ON provisioned slices
"""

__version__ = "0.1.0"
