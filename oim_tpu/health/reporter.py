"""Controller-side health telemetry: agent chip health → leased registry keys.

One thread per controller, started next to the ``_register_loop`` address
heartbeat (Controller.start) and stopped with it (Controller.close).  Each
interval it scrapes the device plane's ``get_health`` and re-publishes one
leased key per chip (``health/<controller_id>/<chip_id>``), so:

- a state change propagates within one interval (the FleetMonitor watches,
  nothing polls), and
- a crashed controller's whole health subtree *expires* a few missed
  intervals later — the same lease discipline as the address key, which is
  what lets the registry side declare a controller dead without ever
  dialing it.

Scrapes use their own short-timeout agent connection (the Controller's RPC
path must never block behind a wedged telemetry scrape), re-dialed after
any failure.  A daemon that does not serve ``get_health`` (the C++ agent
today) degrades to ``get_chips`` with every chip reported OK — allocation
occupancy and liveness still flow; only the state channel is flat.
"""

from __future__ import annotations

import threading
import time

from oim_tpu import log
from oim_tpu.agent import Agent, METHOD_NOT_FOUND, is_agent_error
from oim_tpu.common import metrics, resilience
from oim_tpu.common.regdial import registry_channel
from oim_tpu.health import states
from oim_tpu.spec import REGISTRY, oim_pb2

DEFAULT_HEALTH_INTERVAL = 5.0


class HealthReporter:
    """Scrape-and-publish loop for one controller's chip health."""

    def __init__(
        self,
        controller_id: str,
        agent_socket: str,
        registry_address: str,
        tls=None,
        interval: float = DEFAULT_HEALTH_INTERVAL,
        scrape_timeout: float = 2.0,
        retry: resilience.RetryPolicy | None = None,
    ) -> None:
        self.controller_id = controller_id
        self.agent_socket = agent_socket
        self.registry_address = registry_address
        self.tls = tls
        self.interval = interval
        self.scrape_timeout = scrape_timeout
        # Publish-hop retries bounded to one interval: losing one beat of
        # a 3-beat lease to a registry blip is exactly what the lease
        # budget is for, but losing TWO beats risks a false
        # controller-dead eviction — retry within the beat instead.
        self.retry = (
            retry
            if retry is not None
            else resilience.RetryPolicy.for_heartbeat(interval)
        )
        # One-shot dial policy: the scrape hop must stay bounded to ~one
        # scrape_timeout per cycle (the reporter's own loop IS the retry
        # — next interval, fresh dial); an env-default ladder here could
        # outlast the whole beat.  ConnCache dials outside its lock, so
        # close() never stalls behind a wedged daemon's connect, and
        # latches on close so a late-landing dial cannot leak.
        self._agent_cache = resilience.ConnCache(
            lambda: Agent(
                self.agent_socket,
                timeout=self.scrape_timeout,
                retry=resilience.RetryPolicy.one_shot(),
            )
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._reports = metrics.registry().counter(
            "oim_health_reports_total",
            "Health report publish cycles, by outcome.",
            ("controller", "outcome"),
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "HealthReporter":
        """Idempotent: a second start while running is a no-op."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="controller-health"
        )
        self._thread.start()
        return self

    def close(self) -> None:
        """Idempotent stop; joins the loop and drops the scrape connection
        (latched — a dial in flight when close() ran is closed on
        arrival, not installed)."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            self._thread = None
        self._agent_cache.close()

    def _run(self) -> None:
        while True:
            try:
                self.report_once()
                self._reports.inc(self.controller_id, "ok")
            except Exception as exc:
                # Telemetry must never die: a transient agent or registry
                # failure costs one interval, not the whole channel.
                self._reports.inc(self.controller_id, "error")
                if not self._stop.is_set():
                    log.current().warning(
                        "health report failed",
                        controller=self.controller_id,
                        error=str(exc),
                    )
            if self._stop.wait(self.interval):
                return

    # -- one cycle ---------------------------------------------------------

    def scrape(self) -> list[dict]:
        """Chip health from the device plane, on the telemetry-only
        connection (dropped and re-dialed after any failure)."""
        try:
            agent = self._get_agent()
            try:
                return agent.get_health()
            except Exception as exc:
                if is_agent_error(exc, METHOD_NOT_FOUND):
                    # Health-oblivious daemon: liveness + occupancy only.
                    return [
                        {
                            "chip_id": c["chip_id"],
                            "health": states.OK,
                            "ici_link_errors": 0,
                            "allocation": c.get("allocation", ""),
                        }
                        for c in agent.get_chips()
                    ]
                raise
        except BaseException:
            self._drop_agent()
            raise

    def report_once(self) -> int:
        """Scrape and publish every chip's health key; returns the number
        of keys written.  Lease TTL = 3 intervals, matching the address
        heartbeat's missed-beats-then-expire discipline."""
        chips = self.scrape()
        ttl = max(1, int(self.interval * 3))
        now = time.time()

        def publish(attempt):
            # Re-publishing every key on retry is safe: SetValue of the
            # same report is idempotent and re-arms the lease.  Each
            # SetValue re-derives the ladder's remaining budget (one
            # clamp shared by N chips would let a hanging registry burn
            # N x clamp per attempt and stall the beat past the deadline
            # the policy promises).
            clamp = attempt.budget_clamp(self.retry.clock)
            with registry_channel(self.registry_address, self.tls) as channel:
                stub = REGISTRY.stub(channel)
                for chip in chips:
                    stub.SetValue(
                        oim_pb2.SetValueRequest(
                            value=oim_pb2.Value(
                                path=states.health_key(
                                    self.controller_id, chip["chip_id"]
                                ),
                                value=states.encode_report(
                                    chip.get("health", states.OK),
                                    chip.get("ici_link_errors", 0),
                                    chip.get("allocation", ""),
                                    now,
                                ),
                            ),
                            ttl_seconds=ttl,
                        ),
                        timeout=clamp(10.0),
                    )

        resilience.call_with_retry(
            publish,
            self.retry,
            component="oim-controller",
            op="PublishHealth",
        )
        return len(chips)

    def _get_agent(self) -> Agent:
        return self._agent_cache.get()

    def _drop_agent(self) -> None:
        self._agent_cache.drop()
