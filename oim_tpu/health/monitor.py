"""Registry-side fault management: watch health keys, classify, evict.

Runs next to the registry (sharing its ``RegistryDB``), fully event-driven:
the only subscription is one ``db.watch`` — the same primitive the
WatchValues dispatcher and the serving router ride — so when no monitor is
attached nothing polls, and when one is, detection latency is the event
hub's, not a poll tick's.

Event classification:

- ``health/<cid>/<chip>`` set → chip telemetry.  FAILED evicts the owning
  allocation immediately; DEGRADED arms a drain grace timer (cancelled if
  the chip recovers before it fires); OK disarms.
- ``<cid>/address`` deleted (explicit or lease expiry) → controller-dead:
  every allocation last seen on that controller is evicted **without any
  RPC to the dead controller** — the monitor's knowledge comes entirely
  from past health reports, so detection is bounded by lease TTL + one
  sweep interval, never by a connect timeout to a dead host.
- ``drain/<cid>`` set (``oimctl drain``) → operator cordon: evict the
  controller's allocations at the operator's request.

Eviction marks the volume in the registry (``evictions/<volume_id>``); the
CSI RemoteBackend refuses to stage a marked volume and ``oimctl remap``
clears the mark (after the policy's remap backoff) to place the volume on
a healthy controller.
"""

from __future__ import annotations

import heapq
import json
import threading
import time
from dataclasses import dataclass
from typing import Callable, Hashable

from oim_tpu import log
from oim_tpu.common import events, metrics
from oim_tpu.health import states


@dataclass
class EvictionPolicy:
    """Knobs for the fault-management loop.

    - ``degraded_grace_s``: a DEGRADED chip is drained only after staying
      degraded this long (transient blips recover for free).
    - ``remap_backoff_s``: an evicted volume may be remapped only this long
      after eviction (lets in-flight teardown settle before the slice is
      rebuilt elsewhere); ``oimctl remap --force`` overrides.
    """

    degraded_grace_s: float = 30.0
    remap_backoff_s: float = 0.0


class _GraceTimer:
    """Lazy one-thread deadline scheduler (the _LeaseSweeper shape, for
    monitor grace periods).  ``arm(key, deadline)`` schedules,
    ``disarm(key)`` cancels; the callback fires OFF the caller's locks.
    No thread exists until the first arm; an idle timer waits on its
    condition, it does not poll."""

    def __init__(self, fire: Callable[[Hashable], None]) -> None:
        self._fire = fire
        self._cond = threading.Condition()
        self._seq: dict[Hashable, int] = {}
        self._armed: dict[Hashable, tuple[float, int]] = {}
        self._heap: list[tuple[float, int, Hashable]] = []
        self._thread: threading.Thread | None = None
        self._closed = False

    def arm(self, key: Hashable, deadline: float) -> None:
        with self._cond:
            if self._closed:
                return
            seq = self._seq.get(key, 0) + 1
            self._seq[key] = seq
            self._armed[key] = (deadline, seq)
            heapq.heappush(self._heap, (deadline, seq, key))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="fleet-grace-timer"
                )
                self._thread.start()
            self._cond.notify()

    def armed(self, key: Hashable) -> bool:
        with self._cond:
            return key in self._armed

    def disarm(self, key: Hashable) -> None:
        with self._cond:
            if key in self._armed:
                self._seq[key] = self._seq.get(key, 0) + 1
                del self._armed[key]  # stale heap entries skip on seq

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._armed.clear()
            thread = self._thread
            self._cond.notify()
        if thread is not None:
            thread.join(timeout=10)

    def _run(self) -> None:
        while True:
            with self._cond:
                if self._closed:
                    return
                now = time.monotonic()
                due: list[Hashable] = []
                while self._heap and self._heap[0][0] <= now:
                    deadline, seq, key = heapq.heappop(self._heap)
                    if self._armed.get(key) == (deadline, seq):
                        del self._armed[key]
                        due.append(key)
                if not due:
                    wait = self._heap[0][0] - now if self._heap else None
                    self._cond.wait(timeout=wait)
                    continue
            for key in due:  # outside the condition: fire may re-arm
                try:
                    self._fire(key)
                except Exception as exc:
                    # A failed drain must cost ONE deadline, not the
                    # timer thread — arm() never respawns a dead one.
                    log.current().error(
                        "grace-timer callback failed",
                        key=str(key),
                        error=str(exc),
                    )


class EvictionEngine:
    """Marks allocations evicted in the registry, once, with metrics."""

    def __init__(self, db, policy: EvictionPolicy | None = None) -> None:
        self.db = db
        self.policy = policy or EvictionPolicy()
        self._lock = threading.Lock()
        self._evictions = metrics.registry().counter(
            "oim_evictions_total",
            "Allocations marked evicted by the fault-management loop.",
            ("reason",),
        )
        self._detect = metrics.registry().histogram(
            "oim_health_detect_seconds",
            "Fault publish → eviction decision latency.",
        )

    def evict(
        self,
        volume_id: str,
        controller_id: str,
        reason: str,
        detail: str = "",
        reported_ts: float | None = None,
    ) -> bool:
        """Mark ``volume_id`` evicted; returns False if already marked
        (idempotent — a flapping health key must not inflate the counter)."""
        key = states.eviction_key(volume_id)
        now = time.time()
        with self._lock:  # lookup→store must be atomic across threads
            if self.db.lookup(key):
                return False
            self.db.store(
                key,
                json.dumps(
                    {
                        "state": "evicted",
                        "controller": controller_id,
                        "reason": reason,
                        "detail": detail,
                        "ts": now,
                        "remap_after": now + self.policy.remap_backoff_s,
                    },
                    separators=(",", ":"),
                ),
            )
        self._evictions.inc(reason)
        if reported_ts:
            self._detect.observe(max(0.0, now - reported_ts))
        events.emit(
            "health.eviction",
            component="fleet-monitor",
            severity=events.WARNING,
            subject=volume_id,
            controller=controller_id,
            reason=reason,
            detail=detail,
        )
        log.current().warning(
            "allocation evicted",
            volume=volume_id,
            controller=controller_id,
            reason=reason,
            detail=detail,
        )
        return True

    def clear(self, volume_id: str) -> None:
        """Lift an eviction mark (the in-process analog of ``oimctl
        remap``'s SetValue delete)."""
        self.db.store(states.eviction_key(volume_id), "")


class FleetMonitor:
    """Watches the registry DB and drives the EvictionEngine."""

    def __init__(
        self,
        db,
        policy: EvictionPolicy | None = None,
        engine: EvictionEngine | None = None,
    ) -> None:
        self.db = db
        self.policy = policy or EvictionPolicy()
        self.engine = engine or EvictionEngine(db, self.policy)
        # RLock: an eviction store can re-dispatch events on this thread.
        self._lock = threading.RLock()
        self._live: dict[tuple[str, str], dict] = {}  # (cid, chip) → report
        # Last-known chip → allocation per controller.  Survives health-key
        # lease expiry (a dying controller's health keys may expire BEFORE
        # its address does); cleared only once the controller-dead eviction
        # has consumed it.
        self._allocs: dict[str, dict[str, str]] = {}
        self._controllers: set[str] = set()
        self._cordoned: set[str] = set()  # drain/<cid> present
        # volume → wall-clock time its eviction mark was last cleared
        # (oimctl remap).  Telemetry PUBLISHED before the clear must not
        # re-evict the freshly remapped volume — the old controller's
        # in-flight report still names it until its next scrape.
        self._cleared: dict[str, float] = {}
        # Programmatic fault subscription (add_listener): consumers —
        # the autoscaler's replacement trigger — ride THIS monitor's
        # classification instead of running a second registry watch and
        # re-deriving grace timers / spoof checks from raw events.
        self._listeners: dict[int, tuple[Callable | None, Callable | None]] = {}
        self._next_listener = 0
        self._timer = _GraceTimer(self._grace_fired)
        self._cancel_watch: Callable[[], None] | None = None
        self._chips_gauge = metrics.registry().gauge(
            "oim_health_chips",
            "Chips by reported health state.",
            ("controller", "state"),
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetMonitor":
        if self._cancel_watch is not None:
            return self
        # Subscribe BEFORE the snapshot so no event between the two is
        # lost; handlers are idempotent, so a duplicate is harmless (the
        # WatchValues reconcile discipline).
        self._cancel_watch = self.db.watch("", self._on_event)
        for path, value in self.db.items(""):
            self._on_event(path, value)
        return self

    def close(self) -> None:
        if self._cancel_watch is not None:
            self._cancel_watch()
            self._cancel_watch = None
        self._timer.close()
        with self._lock:
            controllers = list(self._controllers)
            self._controllers.clear()
            self._live.clear()
            self._allocs.clear()
        for cid in controllers:
            for state in states.HEALTH_STATES:
                self._chips_gauge.remove(cid, state)

    # -- programmatic subscription -----------------------------------------

    def add_listener(
        self,
        on_eviction: Callable[[str, str, str], None] | None = None,
        on_controller_dead: Callable[[str], None] | None = None,
    ) -> Callable[[], None]:
        """Subscribe to the monitor's classification.  ``on_eviction``
        fires as ``(volume_id, controller_id, reason)`` once per FRESH
        eviction (the EvictionEngine's idempotency dedupes a flapping
        health key before listeners see it); ``on_controller_dead``
        fires as ``(controller_id,)`` on every address-loss event.
        Returns a remove function.  Callbacks run on whatever thread
        classified the event and must not block; an exception in one
        never reaches the watch dispatch (or other listeners)."""
        with self._lock:
            lid = self._next_listener
            self._next_listener += 1
            self._listeners[lid] = (on_eviction, on_controller_dead)

        def remove() -> None:
            with self._lock:
                self._listeners.pop(lid, None)

        return remove

    def _fire_listeners(self, index: int, *args) -> None:
        with self._lock:
            callbacks = [
                fns[index] for fns in self._listeners.values()
                if fns[index] is not None
            ]
        for callback in callbacks:  # outside the lock: may re-enter us
            try:
                callback(*args)
            except Exception as exc:
                log.current().error(
                    "fleet-monitor listener failed", error=str(exc)
                )

    # -- observability -----------------------------------------------------

    def chip_states(self) -> dict[tuple[str, str], str]:
        """(controller, chip) → state snapshot (oimctl/tests)."""
        with self._lock:
            return {k: r["state"] for k, r in self._live.items()}

    def _claimed_elsewhere(self, volume: str, cid: str) -> bool:
        """True when another controller's telemetry currently claims
        ``volume``.  Defense in depth behind the registry authz: a buggy
        or compromised controller can write only its own ``health/<id>/*``
        subtree, so without this check one spoofed report naming a
        foreign volume would evict it fleet-wide."""
        with self._lock:
            for other, chips in self._allocs.items():
                if other != cid and volume in chips.values():
                    return True
        return False

    def _evict_from_report(
        self, volume: str, cid: str, reason: str, detail: str,
        reported_ts: float | None = None,
    ) -> None:
        if reported_ts:
            with self._lock:
                cleared_at = self._cleared.get(volume, 0.0)
            if reported_ts <= cleared_at:
                # Telemetry published before the operator's remap cleared
                # the mark: the pre-remap state, not news.
                return
        if self._claimed_elsewhere(volume, cid):
            log.current().warning(
                "ignoring eviction for foreign volume",
                volume=volume,
                controller=cid,
                reason=reason,
            )
            return
        if self.engine.evict(
            volume, cid, reason, detail=detail, reported_ts=reported_ts
        ):
            # Fresh evictions only: the engine's idempotent mark is the
            # dedupe, so a flapping health key costs one notification.
            self._fire_listeners(0, volume, cid, reason)

    def _update_gauge(self, cid: str) -> None:
        with self._lock:
            counts = {s: 0 for s in states.HEALTH_STATES}
            for (rcid, _), report in self._live.items():
                if rcid == cid:
                    counts[report["state"]] += 1
        for state, n in counts.items():
            self._chips_gauge.set(n, cid, state)

    # -- event classification ----------------------------------------------

    def _on_event(self, path: str, value: str) -> None:
        """Classify one registry mutation.  Never raises: this runs
        inside the DB's watch dispatch, on whatever thread committed the
        mutation — an exception here would propagate into the lease
        sweeper (killing ALL expiry for the registry) or abort a
        client's SetValue RPC."""
        try:
            self._classify(path, value)
        except Exception as exc:
            log.current().error(
                "fleet monitor event failed", path=path, error=str(exc)
            )

    def _classify(self, path: str, value: str) -> None:
        health = states.parse_health_path(path)
        if health is not None:
            self._on_health(health[0], health[1], value)
            return
        cid = states.parse_address_path(path)
        if cid is not None and value == "":
            self._on_controller_dead(cid)
            return
        cid = states.parse_drain_path(path)
        if cid is not None:
            if value != "":
                self._on_drain(cid, value)
            else:
                with self._lock:
                    self._cordoned.discard(cid)
            return
        volume = states.parse_eviction_path(path)
        if volume is not None and value == "":
            with self._lock:
                self._cleared[volume] = time.time()
                if len(self._cleared) > 4096:  # bound the remap history
                    oldest = min(self._cleared, key=self._cleared.get)
                    del self._cleared[oldest]

    def _on_health(self, cid: str, chip: str, value: str) -> None:
        key = (cid, chip)
        if value == "":
            # Key expired/deleted: the chip stops counting toward live
            # state but its last-known allocation is retained for the
            # controller-dead path.
            with self._lock:
                known = self._live.pop(key, None)
            if known is not None:
                events.emit(
                    "health.lease-expired",
                    component="fleet-monitor",
                    subject=f"{cid}/{chip}",
                    controller=cid,
                    chip=chip,
                )
            self._timer.disarm(key)
            self._update_gauge(cid)
            return
        report = states.decode_report(value)
        if report is None:
            return  # malformed/foreign value: never kill the watcher
        with self._lock:
            prev = self._live.get(key)
            self._live[key] = report
            self._controllers.add(cid)
            if report["allocation"]:
                self._allocs.setdefault(cid, {})[chip] = report["allocation"]
            else:
                self._allocs.get(cid, {}).pop(chip, None)
        self._update_gauge(cid)
        state = report["state"]
        with self._lock:
            cordoned = cid in self._cordoned
        if cordoned and report["allocation"]:
            # An allocation surfacing on a cordoned controller is evicted
            # on sight — the drain stays in force until uncordon, even
            # across a monitor restart (the cordon set is rebuilt from
            # the drain/ snapshot).
            self._evict_from_report(
                report["allocation"], cid, "drained", f"chip {chip}",
                reported_ts=report["ts"],
            )
            return
        if state == states.FAILED:
            self._timer.disarm(key)
            if report["allocation"]:
                self._evict_from_report(
                    report["allocation"],
                    cid,
                    "chip-failed",
                    f"chip {chip}",
                    reported_ts=report["ts"],
                )
        elif state == states.DEGRADED:
            # Arm on the transition INTO degraded (refreshes of a
            # still-degraded chip must not push the drain deadline out
            # forever) — and ALSO when the chip's allocation changed: a
            # volume placed onto an already-degraded chip after an
            # earlier grace fired gets its own full grace, not a free
            # pass.
            fresh = (
                prev is None
                or prev["state"] != states.DEGRADED
                or prev.get("allocation", "") != report["allocation"]
            )
            if fresh and not self._timer.armed(key):
                self._timer.arm(
                    key, time.monotonic() + self.policy.degraded_grace_s
                )
        else:  # OK — recovery cancels a pending drain
            self._timer.disarm(key)

    def _grace_fired(self, key) -> None:
        cid, chip = key
        with self._lock:
            report = self._live.get(key)
            alloc = (
                (report or {}).get("allocation")
                or self._allocs.get(cid, {}).get(chip, "")
            )
        if report is not None and report["state"] == states.DEGRADED and alloc:
            self._evict_from_report(
                alloc,
                cid,
                "chip-degraded",
                f"chip {chip} degraded > {self.policy.degraded_grace_s}s",
                reported_ts=report["ts"],
            )

    def _on_controller_dead(self, cid: str) -> None:
        with self._lock:
            allocs = sorted(set(self._allocs.pop(cid, {}).values()))
            for key in [k for k in self._live if k[0] == cid]:
                del self._live[key]
                self._timer.disarm(key)
        if allocs:
            events.emit(
                "health.controller-dead",
                component="fleet-monitor",
                severity=events.ERROR,
                subject=cid,
                volumes=len(allocs),
            )
        for volume in allocs:
            self._evict_from_report(volume, cid, "controller-dead", "")
        # After the evictions so a listener reacting to the death sees
        # the marks already placed; fired even with zero live
        # allocations — a consumer may track resources (serve replicas)
        # the health telemetry does not.
        self._fire_listeners(1, cid)
        self._update_gauge(cid)

    def _on_drain(self, cid: str, value: str) -> None:
        with self._lock:
            self._cordoned.add(cid)
            allocs = sorted(set(self._allocs.get(cid, {}).values()))
        events.emit(
            "health.drain",
            component="fleet-monitor",
            severity=events.WARNING,
            subject=cid,
            reason=value,
            volumes=len(allocs),
        )
        for volume in allocs:
            self._evict_from_report(volume, cid, "drained", value)
