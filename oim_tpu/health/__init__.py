"""Fleet health & fault management (new subsystem; no reference analog —
the reference assumes a healthy device plane and lets dead controllers'
registrations rot, reference registry.go lease-free SetValue).

Four layers, built entirely on the registry's existing lease + watch
primitives:

- device plane: per-chip health + deterministic fault injection
  (``oim_tpu.agent.fake`` / ``Agent.get_health``/``inject_fault``)
- controller: :class:`HealthReporter` publishes leased
  ``health/<controller>/<chip>`` keys each interval
- registry side: :class:`FleetMonitor` classifies events (chip-failed,
  chip-degraded, controller-dead, operator drain) and drives the
  :class:`EvictionEngine`, which marks ``evictions/<volume>`` so the CSI
  RemoteBackend refuses to stage the volume until ``oimctl remap``
- operator surface: ``oimctl health`` / ``drain`` / ``uncordon`` /
  ``remap`` plus ``oim_health_*`` and ``oim_evictions_total`` metrics
"""

from oim_tpu.health import states
from oim_tpu.health.monitor import EvictionEngine, EvictionPolicy, FleetMonitor
from oim_tpu.health.reporter import DEFAULT_HEALTH_INTERVAL, HealthReporter

__all__ = [
    "DEFAULT_HEALTH_INTERVAL",
    "EvictionEngine",
    "EvictionPolicy",
    "FleetMonitor",
    "HealthReporter",
    "states",
]
