"""Health-state vocabulary and registry key/value schema.

The fleet health loop is three registry keyspaces (all plain KV, so every
existing primitive — leases, watch, oimctl, authz — applies unchanged):

- ``health/<controller_id>/<chip_id>`` — one leased key per chip, refreshed
  by the controller's HealthReporter each interval; the value is a JSON
  report (state, ICI link errors, owning allocation, publish timestamp).
  Lease expiry (controller death) deletes the key with a watch event.
- ``drain/<controller_id>`` — operator cordon mark (``oimctl drain``);
  deleting it (``oimctl uncordon``) lifts the cordon.
- ``evictions/<volume_id>`` — set by the EvictionEngine; while present the
  CSI RemoteBackend refuses to stage the volume and ``oimctl remap`` is the
  operator path back to a healthy controller.
"""

from __future__ import annotations

import json
from typing import Any

OK = "OK"
DEGRADED = "DEGRADED"
FAILED = "FAILED"
HEALTH_STATES = (OK, DEGRADED, FAILED)

HEALTH_PREFIX = "health"
DRAIN_PREFIX = "drain"
EVICTIONS_PREFIX = "evictions"


def health_key(controller_id: str, chip_id: int | str) -> str:
    return f"{HEALTH_PREFIX}/{controller_id}/{chip_id}"


def drain_key(controller_id: str) -> str:
    return f"{DRAIN_PREFIX}/{controller_id}"


def eviction_key(volume_id: str) -> str:
    return f"{EVICTIONS_PREFIX}/{volume_id}"


def parse_health_path(path: str) -> tuple[str, str] | None:
    """``health/<cid>/<chip>`` → (cid, chip), else None."""
    parts = path.split("/")
    if len(parts) == 3 and parts[0] == HEALTH_PREFIX:
        return parts[1], parts[2]
    return None


def parse_drain_path(path: str) -> str | None:
    parts = path.split("/")
    if len(parts) == 2 and parts[0] == DRAIN_PREFIX:
        return parts[1]
    return None


def parse_eviction_path(path: str) -> str | None:
    parts = path.split("/")
    if len(parts) == 2 and parts[0] == EVICTIONS_PREFIX:
        return parts[1]
    return None


def parse_address_path(path: str) -> str | None:
    """``<cid>/address`` → cid, else None (``serve/<id>/address`` and other
    deeper keys are different planes and excluded)."""
    parts = path.split("/")
    if len(parts) == 2 and parts[1] == "address":
        return parts[0]
    return None


def encode_report(
    state: str, link_errors: int, allocation: str, ts: float
) -> str:
    return json.dumps(
        {
            "state": state,
            "link_errors": int(link_errors),
            "allocation": allocation,
            "ts": ts,
        },
        separators=(",", ":"),
    )


def decode_report(value: str) -> dict[str, Any] | None:
    """Parse a health report value; None for malformed/foreign values (a
    watcher must never die on one bad key)."""
    try:
        report = json.loads(value)
    except ValueError:
        return None
    if not isinstance(report, dict) or report.get("state") not in HEALTH_STATES:
        return None
    report.setdefault("link_errors", 0)
    report.setdefault("allocation", "")
    report.setdefault("ts", 0.0)
    return report
