"""Perfdash-compatible benchmark result schema.

≙ reference test/e2e/perftype/perftype.go:26-53 — the one metrics artifact
the reference ships.  Same JSON shape (``version``/``dataItems`` with
``data``/``unit``/``labels`` buckets) and the same result-framing tags, so
the emitted blocks drop straight into perfdash-style tooling.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

PERF_RESULT_TAG = "[Result:Performance]"
PERF_RESULT_END = "[Finish:Performance]"

CURRENT_VERSION = "v1"


@dataclass
class DataItem:
    """One data point: bucket -> value (e.g. "Perc90" -> 23.5).  Items with
    the same label combination must share buckets and unit."""

    data: dict[str, float] = field(default_factory=dict)
    unit: str = ""
    labels: dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict:
        out: dict = {"data": self.data, "unit": self.unit}
        if self.labels:
            out["labels"] = self.labels
        return out


@dataclass
class PerfData:
    version: str = CURRENT_VERSION
    data_items: list[DataItem] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)

    def add(self, unit: str, labels: dict[str, str], **buckets: float) -> DataItem:
        item = DataItem(data=dict(buckets), unit=unit, labels=labels)
        self.data_items.append(item)
        return item

    def to_json(self) -> dict:
        out: dict = {
            "version": self.version,
            "dataItems": [i.to_json() for i in self.data_items],
        }
        if self.labels:
            out["labels"] = self.labels
        return out

    def render(self) -> str:
        """The framed block analysis tools scan for (≙ PerfResultTag /
        PerfResultEnd framing in the reference)."""
        return (
            f"{PERF_RESULT_TAG}\n"
            + json.dumps(self.to_json(), indent=2, sort_keys=True)
            + f"\n{PERF_RESULT_END}"
        )


def parse(text: str) -> list[PerfData]:
    """Extract every framed PerfData block from mixed output."""
    results = []
    rest = text
    while True:
        start = rest.find(PERF_RESULT_TAG)
        if start < 0:
            return results
        end = rest.find(PERF_RESULT_END, start)
        if end < 0:
            return results
        blob = rest[start + len(PERF_RESULT_TAG):end]
        raw = json.loads(blob)
        results.append(
            PerfData(
                version=raw.get("version", ""),
                data_items=[
                    DataItem(
                        data=i.get("data", {}),
                        unit=i.get("unit", ""),
                        labels=i.get("labels", {}),
                    )
                    for i in raw.get("dataItems", [])
                ],
                labels=raw.get("labels", {}),
            )
        )
        rest = rest[end + len(PERF_RESULT_END):]
