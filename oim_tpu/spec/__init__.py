"""Wire spec: generated protobuf bindings + gRPC service descriptors.

≙ reference pkg/spec: the generated code lives under ``gen/`` (from
``make gen``; source of truth is doc/spec.md and proto/csi/v1/csi.proto).
Because the image has protoc but not the grpc python plugin, service
client/server plumbing is provided by ``oim_tpu.spec.rpc`` service
descriptors instead of generated stubs.
"""

from oim_tpu.spec.gen.oim.v1 import oim_pb2
from oim_tpu.spec.gen.csi.v1 import csi_pb2
from oim_tpu.spec.gen.csi.v0 import csi_pb2 as csi0_pb2

from oim_tpu.spec.rpc import (
    ServiceSpec,
    REGISTRY,
    CONTROLLER,
    CSI_IDENTITY,
    CSI_CONTROLLER,
    CSI_NODE,
    CSI0_IDENTITY,
    CSI0_CONTROLLER,
    CSI0_NODE,
)

__all__ = [
    "oim_pb2",
    "csi_pb2",
    "csi0_pb2",
    "ServiceSpec",
    "REGISTRY",
    "CONTROLLER",
    "CSI_IDENTITY",
    "CSI_CONTROLLER",
    "CSI_NODE",
    "CSI0_IDENTITY",
    "CSI0_CONTROLLER",
    "CSI0_NODE",
]
