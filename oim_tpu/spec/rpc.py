"""gRPC service descriptors: typed stubs and server registration.

Replaces protoc-generated service code (the image lacks the grpc protoc
plugin): a ``ServiceSpec`` names a service's methods with their request/reply
message classes and can mint client stubs (``stub``) and server registrars
(``registrar``) from them.  Method paths are canonical
(``/package.Service/Method``) so the wire format matches generated peers.
"""

from __future__ import annotations

from typing import Callable

import grpc

from oim_tpu.spec.gen.csi.v0 import csi_pb2 as csi0_pb2
from oim_tpu.spec.gen.csi.v1 import csi_pb2
from oim_tpu.spec.gen.oim.v1 import oim_pb2


# Method kinds.  The 2-tuple (req, reply) form of a method entry means
# UNARY; streaming methods use a 3-tuple (req, reply, kind).
UNARY = "unary"
SERVER_STREAM = "server_stream"  # unary request → stream of replies
BIDI_STREAM = "bidi_stream"  # stream of requests → stream of replies

_HANDLER_FACTORY = {
    UNARY: grpc.unary_unary_rpc_method_handler,
    SERVER_STREAM: grpc.unary_stream_rpc_method_handler,
    BIDI_STREAM: grpc.stream_stream_rpc_method_handler,
}


def _parse_entry(entry):
    if len(entry) == 2:
        req_cls, reply_cls = entry
        return req_cls, reply_cls, UNARY
    req_cls, reply_cls, kind = entry
    if kind not in _HANDLER_FACTORY:
        raise ValueError(f"unknown method kind {kind!r}")
    return req_cls, reply_cls, kind


class ServiceSpec:
    def __init__(self, full_name: str, methods: dict[str, tuple]):
        self.full_name = full_name
        self.methods = methods

    def method_path(self, name: str) -> str:
        if name not in self.methods:
            raise KeyError(f"{self.full_name} has no method {name}")
        return f"/{self.full_name}/{name}"

    def stub(self, channel: grpc.Channel) -> "Stub":
        return Stub(self, channel)

    def registrar(self, servicer: object) -> Callable[[grpc.Server], None]:
        """A registrar adding ``servicer`` (an object with one method per RPC
        name — ``(request, context) -> reply`` for unary, a generator of
        replies for server-streaming, ``(request_iterator, context)`` for
        bidi) to a server."""
        handlers = {}
        for name, entry in self.methods.items():
            behavior = getattr(servicer, name, None)
            if behavior is None:
                continue
            req_cls, reply_cls, kind = _parse_entry(entry)
            handlers[name] = _HANDLER_FACTORY[kind](
                behavior,
                request_deserializer=req_cls.FromString,
                response_serializer=reply_cls.SerializeToString,
            )
        if not handlers:
            raise ValueError(
                f"servicer {servicer!r} implements no {self.full_name} methods"
            )
        generic = grpc.method_handlers_generic_handler(self.full_name, handlers)

        def register(server: grpc.Server) -> None:
            server.add_generic_rpc_handlers((generic,))

        return register


class Stub:
    """Per-service client: one callable attribute per method.

    ``stub.MapVolume(request, timeout=..., metadata=...)`` — metadata is how
    proxied calls carry the ``controllerid`` routing key (≙ reference
    pkg/oim-csi-driver/remote.go:78).  Streaming methods mint the matching
    channel callable: server-streaming stubs return a response iterator,
    bidi stubs take a request iterator and return a response iterator.
    """

    def __init__(self, spec: ServiceSpec, channel: grpc.Channel):
        self._spec = spec
        for name, entry in spec.methods.items():
            req_cls, reply_cls, kind = _parse_entry(entry)
            factory = {
                UNARY: channel.unary_unary,
                SERVER_STREAM: channel.unary_stream,
                BIDI_STREAM: channel.stream_stream,
            }[kind]
            callable_ = factory(
                spec.method_path(name),
                request_serializer=req_cls.SerializeToString,
                response_deserializer=reply_cls.FromString,
            )
            setattr(self, name, callable_)


REGISTRY = ServiceSpec(
    "oim.v1.Registry",
    {
        "SetValue": (oim_pb2.SetValueRequest, oim_pb2.SetValueReply),
        "GetValues": (oim_pb2.GetValuesRequest, oim_pb2.GetValuesReply),
        "WatchValues": (
            oim_pb2.WatchValuesRequest,
            oim_pb2.WatchValuesReply,
            SERVER_STREAM,
        ),
    },
)

CONTROLLER = ServiceSpec(
    "oim.v1.Controller",
    {
        "MapVolume": (oim_pb2.MapVolumeRequest, oim_pb2.MapVolumeReply),
        "UnmapVolume": (oim_pb2.UnmapVolumeRequest, oim_pb2.UnmapVolumeReply),
        "ProvisionSlice": (
            oim_pb2.ProvisionSliceRequest,
            oim_pb2.ProvisionSliceReply,
        ),
        "CheckSlice": (oim_pb2.CheckSliceRequest, oim_pb2.CheckSliceReply),
        "GetTopology": (oim_pb2.GetTopologyRequest, oim_pb2.GetTopologyReply),
        "ListSlices": (oim_pb2.ListSlicesRequest, oim_pb2.ListSlicesReply),
    },
)

CSI_IDENTITY = ServiceSpec(
    "csi.v1.Identity",
    {
        "GetPluginInfo": (
            csi_pb2.GetPluginInfoRequest,
            csi_pb2.GetPluginInfoResponse,
        ),
        "GetPluginCapabilities": (
            csi_pb2.GetPluginCapabilitiesRequest,
            csi_pb2.GetPluginCapabilitiesResponse,
        ),
        "Probe": (csi_pb2.ProbeRequest, csi_pb2.ProbeResponse),
    },
)

CSI_CONTROLLER = ServiceSpec(
    "csi.v1.Controller",
    {
        "CreateVolume": (csi_pb2.CreateVolumeRequest, csi_pb2.CreateVolumeResponse),
        "DeleteVolume": (csi_pb2.DeleteVolumeRequest, csi_pb2.DeleteVolumeResponse),
        "ValidateVolumeCapabilities": (
            csi_pb2.ValidateVolumeCapabilitiesRequest,
            csi_pb2.ValidateVolumeCapabilitiesResponse,
        ),
        "ListVolumes": (csi_pb2.ListVolumesRequest, csi_pb2.ListVolumesResponse),
        "GetCapacity": (csi_pb2.GetCapacityRequest, csi_pb2.GetCapacityResponse),
        "ControllerGetCapabilities": (
            csi_pb2.ControllerGetCapabilitiesRequest,
            csi_pb2.ControllerGetCapabilitiesResponse,
        ),
    },
)

# -- CSI 0.3 legacy personality (≙ reference pkg/spec/csi/v0 +
# driver0.go) --------------------------------------------------------------

CSI0_IDENTITY = ServiceSpec(
    "csi.v0.Identity",
    {
        "GetPluginInfo": (
            csi0_pb2.GetPluginInfoRequest,
            csi0_pb2.GetPluginInfoResponse,
        ),
        "GetPluginCapabilities": (
            csi0_pb2.GetPluginCapabilitiesRequest,
            csi0_pb2.GetPluginCapabilitiesResponse,
        ),
        "Probe": (csi0_pb2.ProbeRequest, csi0_pb2.ProbeResponse),
    },
)

CSI0_CONTROLLER = ServiceSpec(
    "csi.v0.Controller",
    {
        "CreateVolume": (
            csi0_pb2.CreateVolumeRequest,
            csi0_pb2.CreateVolumeResponse,
        ),
        "DeleteVolume": (
            csi0_pb2.DeleteVolumeRequest,
            csi0_pb2.DeleteVolumeResponse,
        ),
        "ValidateVolumeCapabilities": (
            csi0_pb2.ValidateVolumeCapabilitiesRequest,
            csi0_pb2.ValidateVolumeCapabilitiesResponse,
        ),
        "GetCapacity": (
            csi0_pb2.GetCapacityRequest,
            csi0_pb2.GetCapacityResponse,
        ),
        "ControllerGetCapabilities": (
            csi0_pb2.ControllerGetCapabilitiesRequest,
            csi0_pb2.ControllerGetCapabilitiesResponse,
        ),
    },
)

CSI0_NODE = ServiceSpec(
    "csi.v0.Node",
    {
        "NodeStageVolume": (
            csi0_pb2.NodeStageVolumeRequest,
            csi0_pb2.NodeStageVolumeResponse,
        ),
        "NodeUnstageVolume": (
            csi0_pb2.NodeUnstageVolumeRequest,
            csi0_pb2.NodeUnstageVolumeResponse,
        ),
        "NodePublishVolume": (
            csi0_pb2.NodePublishVolumeRequest,
            csi0_pb2.NodePublishVolumeResponse,
        ),
        "NodeUnpublishVolume": (
            csi0_pb2.NodeUnpublishVolumeRequest,
            csi0_pb2.NodeUnpublishVolumeResponse,
        ),
        "NodeGetId": (csi0_pb2.NodeGetIdRequest, csi0_pb2.NodeGetIdResponse),
        "NodeGetCapabilities": (
            csi0_pb2.NodeGetCapabilitiesRequest,
            csi0_pb2.NodeGetCapabilitiesResponse,
        ),
        "NodeGetInfo": (
            csi0_pb2.NodeGetInfoRequest,
            csi0_pb2.NodeGetInfoResponse,
        ),
    },
)

CSI_NODE = ServiceSpec(
    "csi.v1.Node",
    {
        "NodeStageVolume": (
            csi_pb2.NodeStageVolumeRequest,
            csi_pb2.NodeStageVolumeResponse,
        ),
        "NodeUnstageVolume": (
            csi_pb2.NodeUnstageVolumeRequest,
            csi_pb2.NodeUnstageVolumeResponse,
        ),
        "NodePublishVolume": (
            csi_pb2.NodePublishVolumeRequest,
            csi_pb2.NodePublishVolumeResponse,
        ),
        "NodeUnpublishVolume": (
            csi_pb2.NodeUnpublishVolumeRequest,
            csi_pb2.NodeUnpublishVolumeResponse,
        ),
        "NodeGetCapabilities": (
            csi_pb2.NodeGetCapabilitiesRequest,
            csi_pb2.NodeGetCapabilitiesResponse,
        ),
        "NodeGetInfo": (csi_pb2.NodeGetInfoRequest, csi_pb2.NodeGetInfoResponse),
    },
)
