"""Hot-path TPU kernels (pallas) with CPU interpreter fallbacks.

The pallas kernels target the real memory hierarchy (HBM→VMEM→MXU/VPU,
/opt/skills/guides/pallas_guide.md); on non-TPU backends they run in
interpreter mode so the whole framework stays testable on CPU — the compute
analog of the control plane's fake-device mode.
"""

from oim_tpu.ops.rmsnorm import rmsnorm, reference_rmsnorm
from oim_tpu.ops.flash_attention import flash_attention, reference_attention
from oim_tpu.ops.fused_ce import fused_linear_ce, reference_linear_ce
from oim_tpu.ops.rope import apply_rope, rope_frequencies

__all__ = [
    "rmsnorm",
    "reference_rmsnorm",
    "flash_attention",
    "reference_attention",
    "fused_linear_ce",
    "reference_linear_ce",
    "apply_rope",
    "rope_frequencies",
]
