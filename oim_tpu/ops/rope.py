"""Rotary position embeddings (plain JAX — XLA fuses these into the
surrounding projections; a kernel would buy nothing)."""

from __future__ import annotations

import math

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int, theta: float = 10000.0, scaling: tuple = ()
) -> jnp.ndarray:
    """Inverse frequencies for the rotated half-pairs: [head_dim // 2].

    ``scaling`` is the Llama-3.1 long-context frequency remap as a
    4-tuple ``(factor, low_freq_factor, high_freq_factor,
    original_max_position)`` (empty = plain RoPE): wavelengths shorter
    than ``original/high`` keep their frequency, longer than
    ``original/low`` divide by ``factor``, and the band between
    interpolates smoothly — the exact piecewise rule HF's reference
    applies, so imported checkpoints reproduce their source numerics
    (models/hf.py).
    """
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**exponent)
    if not scaling:
        return inv_freq
    factor, low_fac, high_fac, original_max = scaling
    low_wavelen = original_max / low_fac
    high_wavelen = original_max / high_fac
    wavelen = 2.0 * math.pi / inv_freq
    # smooth in [0, 1]: 0 at the long-wavelength edge, 1 at the short.
    smooth = (original_max / wavelen - low_fac) / (high_fac - low_fac)
    smooth = jnp.clip(smooth, 0.0, 1.0)
    blended = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    return jnp.where(
        wavelen < high_wavelen,
        inv_freq,
        jnp.where(wavelen > low_wavelen, inv_freq / factor, blended),
    )


def apply_rope(x, positions, theta: float = 10000.0, scaling: tuple = ()):
    """Rotate [..., T, H, D] by per-token ``positions`` [..., T].

    Positions are *global* sequence positions — under sequence parallelism
    the caller passes offsets for its shard, which keeps ring attention
    exact across shard boundaries.
    """
    d = x.shape[-1]
    freqs = rope_frequencies(d, theta, scaling)  # [D/2]
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [..., T, D/2]
    cos = jnp.cos(angles)[..., :, None, :]  # [..., T, 1, D/2]
    sin = jnp.sin(angles)[..., :, None, :]
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    rotated = jnp.stack(
        (x1 * cos - x2 * sin, x1 * sin + x2 * cos), axis=-1
    ).reshape(x.shape)
    return rotated.astype(x.dtype)
