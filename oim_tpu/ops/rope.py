"""Rotary position embeddings (plain JAX — XLA fuses these into the
surrounding projections; a kernel would buy nothing)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 10000.0) -> jnp.ndarray:
    """Inverse frequencies for the rotated half-pairs: [head_dim // 2]."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotate [..., T, H, D] by per-token ``positions`` [..., T].

    Positions are *global* sequence positions — under sequence parallelism
    the caller passes offsets for its shard, which keeps ring attention
    exact across shard boundaries.
    """
    d = x.shape[-1]
    freqs = rope_frequencies(d, theta)  # [D/2]
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [..., T, D/2]
    cos = jnp.cos(angles)[..., :, None, :]  # [..., T, 1, D/2]
    sin = jnp.sin(angles)[..., :, None, :]
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    rotated = jnp.stack(
        (x1 * cos - x2 * sin, x1 * sin + x2 * cos), axis=-1
    ).reshape(x.shape)
    return rotated.astype(x.dtype)
