"""Fused RMSNorm pallas kernel.

RMSNorm is HBM-bandwidth-bound; the fused kernel reads x once per row and
writes once (XLA usually fuses this too — the kernel exists to pin the
layout: full rows in VMEM, one rsqrt on the VPU, no intermediate HBM
round-trip) and keeps the reduction in f32 regardless of input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def reference_rmsnorm(x, w, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)
    # Must match the kernel's output dtype exactly (x's dtype) so the
    # custom-vjp cotangent types line up under mixed bf16/f32 params.
    return normed.astype(x.dtype)


def _kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    normed = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (normed * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rmsnorm(x, w, eps: float = 1e-6):
    """``x * rsqrt(mean(x², axis=-1) + eps) * w`` over the last dimension.

    x: [..., D]; w: [D].  Forward runs the pallas kernel (interpreted off-
    TPU); backward recomputes through the reference formula — RMSNorm is
    cheap enough that rematerializing beats saving activations (HBM trade,
    same policy as jax.checkpoint on the layer).
    """
    return _forward(x, w, eps)


def _forward(x, w, eps):
    orig_shape = x.shape
    d = orig_shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    block_rows = min(rows, 256)
    # Pad rows to a block multiple; pallas grids need static whole blocks.
    padded = pl.cdiv(rows, block_rows) * block_rows
    if padded != rows:
        x2 = jnp.pad(x2, ((0, padded - rows), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((padded, d), x.dtype),
        grid=(padded // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        interpret=_use_interpret(),
    )(x2, w)
    return out[:rows].reshape(orig_shape)


def _fwd(x, w, eps):
    return _forward(x, w, eps), (x, w)


def _bwd(eps, residuals, g):
    x, w = residuals
    _, vjp = jax.vjp(lambda x, w: reference_rmsnorm(x, w, eps), x, w)
    return vjp(g)


rmsnorm.defvjp(_fwd, _bwd)
