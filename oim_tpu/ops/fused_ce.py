"""Fused unembed + cross-entropy (pallas): vocab-tiled, no logits in HBM.

The softmax cross-entropy over a 32k vocabulary is the last big HBM
consumer in the train step: the plain path materializes f32 logits
[B·T, V] (0.5-1 GB at the flagship geometry), reads them for the
logsumexp + target gather, and the backward writes a same-sized dlogits
before the two unembed matmuls (BASELINE.md roofline: unembed + CE is
~19 % of executed FLOPs but its ablation swings 6-18 ms of a ~101 ms
step — the gap between those two numbers is this HBM traffic).

This op never builds the logits tensor.  Forward streams ``wlm`` through
VMEM in ``block_v`` tiles (the innermost, sequential grid dimension) and
keeps the online-logsumexp running max/denominator and the target-logit
accumulator in VMEM scratch across tiles — the same structure as the
flash-attention forward (ops/flash_attention.py), with the vocab axis
playing the role of the key axis.  Per token it emits only the
logsumexp and the target logit: ``nll = lse - target``.

The backward recomputes score tiles from (x, wlm, lse) — probability
``p = exp(s - lse)`` needs no saved logits — and fuses the two unembed
gradients into two kernels mirroring flash's dq/dkv split:

- dx kernel, grid (rows, vocab):  dx  += (p - onehot)·g @ wlmᵀ
- dw kernel, grid (vocab, rows):  dwᵀ += xᵀ @ (p - onehot)·g

Each accumulates in an f32 VMEM scratch over its sequential inner axis
and writes its output block once.  All matmuls ride the MXU with
compute-dtype operands and f32 accumulation (the `_unembed` convention,
models/transformer.py).  Off-TPU the kernels run interpreted; shapes
the tiling cannot cover fall back to an XLA reference path (same
discipline as flash's ragged fallback).

Like every pallas op here, this must run inside fully-manual shard_map
regions only (models/train.py ``_manual_setup`` gates it with
``use_pallas``); under tp the vocab axis is sharded and the global
logsumexp would need a cross-shard combine the XLA path gets for free.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30
_LANES = 128
# Per-row outputs (lse, target) ride [rows, 8] tiles: 8 lanes is the
# narrowest width the mosaic tiling rules allow while keeping rows on
# sublanes (see flash_attention._ROW_LANES).
_ROW_LANES = 8


def _interpret():
    return jax.default_backend() != "tpu"


def reference_linear_ce(x, w, labels):
    """XLA oracle/fallback: per-token NLL via materialized logits.

    Same numerics contract as the kernel: compute-dtype operands, f32
    accumulation (``preferred_element_type``), f32 log-softmax.
    """
    logits = jnp.dot(x, w, preferred_element_type=jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    target = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return lse - target


def _block_n(n: int, want: int):
    """Largest power-of-two row block ≤ want that divides n (≥ 8)."""
    b = want
    while b >= 8:
        if b <= n and n % b == 0:
            return b
        b //= 2
    return None


def _block_v(v: int, want: int):
    """Largest lane-aligned (multiple-of-128) block ≤ want dividing v."""
    best = None
    b = _LANES
    while b <= min(v, want):
        if v % b == 0:
            best = b
        b += _LANES
    return best


def _fwd_kernel(x_ref, w_ref, lbl_ref, lse_ref, tgt_ref, m_scr, l_scr, t_scr,
                *, block_v):
    vi = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        t_scr[...] = jnp.zeros_like(t_scr)

    # Compute-dtype operands on the MXU, f32 accumulator (the _unembed
    # convention) — the cast-to-f32-first alternative would halve MXU rate.
    scores = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_prev, l_prev = m_scr[...], l_scr[...]
    m_curr = jnp.max(scores, axis=1, keepdims=True)      # [bn, 1]
    m_next = jnp.maximum(m_prev, m_curr)                 # [bn, 128]
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.exp(scores - m_next[:, :1])
    l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    m_scr[...] = m_next
    # Target logit: exactly one vocab tile holds each row's label; a
    # masked row-sum accumulates it without a gather (no dynamic indexing
    # on the lane axis).
    col = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    match = col == lbl_ref[...][:, :1]
    t_scr[...] += jnp.sum(
        jnp.where(match, scores, 0.0), axis=1, keepdims=True
    )

    @pl.when(vi == n_v - 1)
    def _finalize():
        lse = m_scr[...] + jnp.log(jnp.maximum(l_scr[...], 1e-30))
        lse_ref[...] = lse[:, :_ROW_LANES]
        tgt_ref[...] = t_scr[...][:, :_ROW_LANES]


def _dlogits_block(x_ref, w_ref, lbl_ref, lse_ref, g_ref, vi, block_v):
    """Recomputed dlogits tile ``(p - onehot) · g`` in compute dtype —
    THE one definition both backward kernels share, so the dx and dw
    numerics can never diverge."""
    scores = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    p = jnp.exp(scores - lse_ref[...][:, :1])            # recomputed probs
    col = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    onehot = (col == lbl_ref[...][:, :1]).astype(jnp.float32)
    return ((p - onehot) * g_ref[...][:, :1]).astype(x_ref.dtype)


def _dx_kernel(x_ref, w_ref, lbl_ref, lse_ref, g_ref, dx_ref, dx_scr,
               *, block_v):
    vi = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        dx_scr[...] = jnp.zeros_like(dx_scr)

    d = _dlogits_block(x_ref, w_ref, lbl_ref, lse_ref, g_ref, vi, block_v)
    dx_scr[...] += jax.lax.dot_general(                  # d @ w.T
        d, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(vi == n_v - 1)
    def _finalize():
        dx_ref[...] = dx_scr[...].astype(dx_ref.dtype)


def _dw_kernel(x_ref, w_ref, lbl_ref, lse_ref, g_ref, dw_ref, dw_scr,
               *, block_v):
    # Grid (vocab, rows): rows are the sequential inner axis so each
    # dw output block accumulates across every row block, then writes once.
    vi, ni = pl.program_id(0), pl.program_id(1)
    n_n = pl.num_programs(1)

    @pl.when(ni == 0)
    def _init():
        dw_scr[...] = jnp.zeros_like(dw_scr)

    d = _dlogits_block(x_ref, w_ref, lbl_ref, lse_ref, g_ref, vi, block_v)
    dw_scr[...] += jax.lax.dot_general(                  # x.T @ d
        x_ref[...], d, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ni == n_n - 1)
    def _finalize():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)


def _row_tile(a):
    """[N] per-row value → [N, _ROW_LANES] lane-replicated tile."""
    return jnp.broadcast_to(a[:, None], (a.shape[0], _ROW_LANES))


def _resolve(n, v, block_n, block_v):
    """(block_n, block_v), auto-tuned where 0 — or None for the XLA
    fallback.  Explicitly passed blocks are validated loudly: a block
    that doesn't tile the array would silently skip rows/columns."""
    for b, size, axis in ((block_n, n, "n"), (block_v, v, "v")):
        if b and (size % b or (axis == "v" and b % _LANES) or (
            axis == "n" and b < 8
        )):
            raise ValueError(
                f"block_{axis}={b} cannot tile {axis}={size} "
                f"(must divide it{'; multiple of 128' if axis == 'v' else '; >= 8'})"
            )
    bn = block_n or _block_n(n, 256)
    bv = block_v or _block_v(v, 1280)
    if bn is None or bv is None:
        return None
    return bn, bv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_ce(x, w, labels, block_n: int = 0, block_v: int = 0):
    """Per-token NLL of ``softmax(x @ w)`` at ``labels`` — [N] f32.

    x: [N, D] compute dtype; w: [D, V] (cast to x.dtype for the MXU);
    labels: [N] int32 in [0, V).  Gradients flow to x and w; the logits
    [N, V] never exist in HBM in either pass.  Zero block sizes
    auto-tune; shapes the tiling cannot cover (row count without a ≥8
    power-of-two divisor, vocab without a lane-aligned divisor) fall
    back to the XLA reference path.
    """
    nll, _ = _fwd(x, w, labels, block_n, block_v)
    return nll


def _forward(x, w, labels, block_n, block_v):
    n, d = x.shape
    v = w.shape[1]
    blocks = _resolve(n, v, block_n, block_v)
    if blocks is None:
        return reference_linear_ce(x, w.astype(x.dtype), labels), None
    bn, bv = blocks
    lbl = _row_tile(labels.astype(jnp.int32))
    lse, tgt = pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=bv),
        out_shape=[
            jax.ShapeDtypeStruct((n, _ROW_LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, _ROW_LANES), jnp.float32),
        ],
        grid=(n // bn, v // bv),
        in_specs=[
            pl.BlockSpec((bn, d), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((d, bv), lambda ni, vi: (0, vi)),
            pl.BlockSpec((bn, _ROW_LANES), lambda ni, vi: (ni, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, _ROW_LANES), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((bn, _ROW_LANES), lambda ni, vi: (ni, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, _LANES), jnp.float32),
            pltpu.VMEM((bn, _LANES), jnp.float32),
            pltpu.VMEM((bn, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(x, w.astype(x.dtype), lbl)
    return lse[:, 0] - tgt[:, 0], lse


def _fwd(x, w, labels, block_n, block_v):
    nll, lse = _forward(x, w, labels, block_n, block_v)
    return nll, (x, w, labels, lse)


def _bwd(block_n, block_v, residuals, g):
    x, w, labels, lse = residuals

    def _reference_bwd():
        _, vjp = jax.vjp(
            lambda x_, w_: reference_linear_ce(
                x_, w_.astype(x_.dtype), labels
            ),
            x, w,
        )
        dx, dw = vjp(g)
        return dx, dw, np.zeros(labels.shape, jax.dtypes.float0)

    if lse is None:  # ragged forward fell back to the reference path
        return _reference_bwd()
    n, d = x.shape
    v = w.shape[1]
    bn, bv = _resolve(n, v, block_n, block_v)
    # The dw tile + its f32 scratch both live in VMEM; halve the vocab
    # block (still a valid divisor: every block is a multiple-of-128
    # divisor chain) until the default no longer crowds the ~16 MB budget.
    bv_dw = bv
    while d * bv_dw * 8 > 8 * 2**20:
        smaller = _block_v(v, bv_dw // 2)
        if not smaller:
            break
        bv_dw = smaller
    if d * bv_dw * 8 > 12 * 2**20:
        # Even the minimum vocab block can't fit next to the weight tile
        # (huge d_model): the kernel would fail at Mosaic compile time,
        # so take the XLA path instead of an over-budget pallas_call.
        return _reference_bwd()
    wc = w.astype(x.dtype)
    lbl = _row_tile(labels.astype(jnp.int32))
    g_rows = _row_tile(g.astype(jnp.float32))
    lse8 = lse  # residual is already the [n, _ROW_LANES] lane-replicated tile

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, block_v=bv),
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
        grid=(n // bn, v // bv),
        in_specs=[
            pl.BlockSpec((bn, d), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((d, bv), lambda ni, vi: (0, vi)),
            pl.BlockSpec((bn, _ROW_LANES), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((bn, _ROW_LANES), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((bn, _ROW_LANES), lambda ni, vi: (ni, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda ni, vi: (ni, 0)),
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        interpret=_interpret(),
    )(x, wc, lbl, lse8, g_rows)

    dw = pl.pallas_call(
        functools.partial(_dw_kernel, block_v=bv_dw),
        out_shape=jax.ShapeDtypeStruct((d, v), w.dtype),
        grid=(v // bv_dw, n // bn),
        in_specs=[
            pl.BlockSpec((bn, d), lambda vi, ni: (ni, 0)),
            pl.BlockSpec((d, bv_dw), lambda vi, ni: (0, vi)),
            pl.BlockSpec((bn, _ROW_LANES), lambda vi, ni: (ni, 0)),
            pl.BlockSpec((bn, _ROW_LANES), lambda vi, ni: (ni, 0)),
            pl.BlockSpec((bn, _ROW_LANES), lambda vi, ni: (ni, 0)),
        ],
        out_specs=pl.BlockSpec((d, bv_dw), lambda vi, ni: (0, vi)),
        scratch_shapes=[pltpu.VMEM((d, bv_dw), jnp.float32)],
        interpret=_interpret(),
    )(x, wc, lbl, lse8, g_rows)

    return dx, dw, np.zeros(labels.shape, jax.dtypes.float0)


fused_linear_ce.defvjp(_fwd, _bwd)
