"""Paged-KV gather/scatter primitives (the vLLM PagedAttention layout).

The serving engine's paged cache is a global pool of fixed-size blocks
``[n_layers, n_blocks, block_size, kv_heads, head_dim]`` plus a
host-managed per-slot block table: logical position ``p`` of slot ``s``
lives at pool row ``table[s, p // block_size] * block_size +
p % block_size``.  These helpers are the only code that knows that
mapping on the device side:

- ``paged_store`` scatters freshly-projected K/V rows into the pool
  through a block table (quantizing when the cache is int8), with the
  OUT-OF-BOUNDS sentinel block id ``n_blocks`` dropping the write —
  padding rows and freed slots write nowhere instead of corrupting a
  reallocated block.
- ``paged_view`` gathers one contiguous per-slot view
  ``[B, n_tables * block_size, kv_heads, head_dim]`` back out, which is
  exactly the dense slot region shape when ``n_tables * block_size ==
  max_len`` — the engine's attention math then runs unchanged on either
  layout, which is what makes paged output token-identical to dense.

Static shapes throughout (XLA compiles one program regardless of which
blocks a slot owns); allocation policy — refcounts, copy-on-write,
prefix aliasing — is host-side bookkeeping in the engine, never traced.

**The sentinel-clamp invariant.**  ``paged_view`` clamps sentinel table
entries to the LAST POOL BLOCK (``n_blocks - 1``) — a gather index must
be in range, and the last block is as good a donor as any.  The rows it
produces are therefore whatever that block currently holds, very much
including another live slot's KV after the block was freed and
reallocated.  That is safe because of a contract every consumer of the
view must uphold: **a sentinel entry only ever covers logical positions
strictly past its row's frontier**, and the shared causal mask
(``k_pos <= q_pos``) assigns those positions weight
``exp(-1e30 - max) == 0`` exactly.  Two corollaries: (1) pool contents
must stay FINITE — the mask zeroes the *weight*, but ``0 × NaN`` in the
probs·V contraction would still poison the output, so nothing may ever
write NaN/Inf into a pool block; (2) the engine must reset a freed
slot's table row to the sentinel BEFORE the block can be handed to a
new owner (``_release_slot_blocks_locked`` does), so an in-flight
chunk's writes for the freed slot drop at the pool edge rather than
landing in the new owner's data.  The flash-decode kernel
(``ops/paged_attention.py``) upholds the same contract the symmetric
way: a sentinel entry's block is never read at all — its grid step
contributes exactly nothing to the online softmax.  Both halves are
pinned by ``tests/test_serve_paged.py``'s freed-and-reallocated
last-block regressions.

Both serve phases can skip the gather entirely:
``ops/paged_attention.py`` holds the Pallas flash-decode kernel that
reads K/V straight from the pool through the block table
(``Engine(paged_kernel=True)``, auto-on for TPU paged engines) and the
flash-prefill kernel that additionally WRITES a prompt segment's K/V
straight into the slot's blocks with fused quant
(``Engine(prefill_kernel=True)``; ``paged_store_blocks`` below is its
block-granular landing scatter) — the gather/scatter path below stays
as the A/B control and the exactness oracle on every backend.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from oim_tpu.ops.quant import quantize_int4, quantize_int8


def _flat_indices(tables, starts, t: int, block_size: int):
    """Pool-flat row index for ``t`` consecutive logical positions per
    row: tables [B, n_tables] (sentinel entry = n_blocks), starts [B]
    → flat [B, t] into the block-flattened pool.  Sentinel blocks map
    past the pool edge, so a ``mode="drop"`` scatter discards them."""
    pos = starts[:, None] + jnp.arange(t)[None, :]  # [B, t]
    blk = jnp.take_along_axis(tables, pos // block_size, axis=1)
    return blk * block_size + pos % block_size


def paged_store(cache, scale, new, tables, starts):
    """Write ``new`` [B, t, KVH, hd] at logical positions ``starts``
    [B] .. ``starts + t - 1`` through ``tables`` [B, n_tables] into the
    one-layer pool ``cache`` [n_blocks, block_size, KVH, hd] —
    quantizing when the cache is quantized (``scale`` [n_blocks,
    block_size, KVH] is not None; the pool's dtype selects the scheme,
    int8 or int4 — the kv4 rung stores half the payload bytes behind
    the same scale plumbing).  Rows whose table entry is the sentinel
    ``n_blocks`` (padding admissions, freed slots) index past the pool
    and are dropped.  The paged counterpart of the engine's
    ``_slot_store``."""
    n_blocks, block_size = cache.shape[0], cache.shape[1]
    flat = _flat_indices(tables, starts, new.shape[1], block_size)
    rows = cache.reshape(n_blocks * block_size, *cache.shape[2:])
    if scale is None:
        rows = rows.at[flat].set(new.astype(cache.dtype), mode="drop")
        return rows.reshape(cache.shape), None
    quantize = quantize_int4 if cache.dtype == jnp.int4 else quantize_int8
    q, s = quantize(new)
    rows = rows.at[flat].set(q, mode="drop")
    srows = scale.reshape(n_blocks * block_size, *scale.shape[2:])
    srows = srows.at[flat].set(s, mode="drop")
    return rows.reshape(cache.shape), srows.reshape(scale.shape)


def paged_store_blocks(cache, scale, blocks, block_scales, ids):
    """Land whole staged blocks in the pool: ``blocks`` [N, block_size,
    KVH, hd] (float payload — already quantized VALUES when the cache
    is int8/int4, so the ``astype`` here is an exact integer cast) at
    pool blocks ``ids`` [N] of ``cache`` [n_blocks, block_size, KVH,
    hd], with ``block_scales`` [N, block_size, KVH] landing in the
    matching ``scale`` plane (or None for fp pools).  Sentinel ids
    (``n_blocks``) drop — same OOB contract as ``paged_store``, one
    block at a time instead of one row at a time.  The landing half of
    the flash-prefill kernel (``ops/paged_attention.py``): the kernel
    STAGES merged blocks into fresh output buffers (never aliasing the
    pool — an aliased in-place write would race Mosaic's double-
    buffered prefetch of a clamped sentinel read against another grid
    step's live overlay of the same block), and this scatter lands
    them.  Live ids are unique by construction (distinct table entries
    of one row name distinct blocks; write windows never cover blocks
    shared across rows), so the scatter's duplicate-index order never
    matters."""
    out = cache.at[ids].set(blocks.astype(cache.dtype), mode="drop")
    if scale is None:
        return out, None
    sout = scale.at[ids].set(block_scales, mode="drop")
    return out, sout


def paged_view(cache, scale, tables):
    """Gather each row's blocks into one contiguous per-slot view:
    cache [n_blocks, block_size, ...] + tables [B, n_tables] →
    [B, n_tables * block_size, ...] (plus the matching scale view, or
    None).  Logical position ``p`` of row ``b`` lands at view row
    ``p`` — the dense slot-region layout — so the engine's causal mask
    and score math apply verbatim.  Sentinel entries clamp to the last
    pool block; the rows they produce are whatever that block holds
    NOW (possibly another slot's live, reallocated KV), which is safe
    only under the sentinel-clamp invariant in the module docstring:
    sentinel-covered positions lie strictly past the row's frontier,
    so the causal mask gives them exactly zero weight."""
    n_blocks = cache.shape[0]
    b, n_tables = tables.shape
    idx = jnp.minimum(tables, n_blocks - 1)
    view = jnp.take(cache, idx, axis=0).reshape(
        b, n_tables * cache.shape[1], *cache.shape[2:]
    )
    if scale is None:
        return view, None
    sview = jnp.take(scale, idx, axis=0).reshape(
        b, n_tables * scale.shape[1], *scale.shape[2:]
    )
    return view, sview


def copy_block(pool, src, dst):
    """Copy one block of a stacked pool [n_layers, n_blocks, ...] —
    the device half of copy-on-write: the allocator picks ``dst`` fresh
    and the engine repoints the diverging slot's table at it, so the
    shared ``src`` is never written again.  ``src``/``dst`` are traced
    scalars (one compile covers every block pair)."""
    row = jax.lax.dynamic_index_in_dim(pool, src, 1, keepdims=False)
    return jax.lax.dynamic_update_index_in_dim(pool, row, dst, 1)


def write_block(pool, row, dst):
    """Write one block's rows ``row`` [n_layers, block_size, ...] at
    block ``dst`` of a stacked pool [n_layers, n_blocks, ...] — the
    ingest half of a KV ship (serve/disagg.py): the engine reserves
    ``dst`` fresh from its allocator and lands the shipped bytes there
    before the continuation's tail prefill dispatches, so the single
    device stream orders import → decode.  ``dst`` is traced (one
    compile covers every destination block)."""
    return jax.lax.dynamic_update_index_in_dim(pool, row, dst, 1)


def read_block(pool, src):
    """Read one block's rows [n_layers, block_size, ...] out of a
    stacked pool [n_layers, n_blocks, ...] — the demotion half of the
    host-RAM overflow tier (ISSUE 15): the engine dispatches this for
    each block it moves to host RAM, then releases the device block;
    the single device stream orders the read before any later prefill
    that reuses the freed block, so the fetched bytes are always the
    pre-reuse contents.  ``src`` is traced (one compile covers every
    source block — the demote path stays compile-free at steady
    state, unlike a shape-varying ``jnp.take`` gather)."""
    return jax.lax.dynamic_index_in_dim(pool, src, 1, keepdims=False)
