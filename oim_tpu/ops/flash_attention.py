"""Flash attention (pallas): blockwise causal attention, O(T) memory.

Forward and backward are both pallas kernels.  The forward streams K/V
through VMEM in ``block_k`` tiles via the grid (k is the innermost, sequential
grid dimension on TPU, so the online-softmax running max/denominator and the
output accumulator live in VMEM scratch across k steps and the [T, T] score
matrix never exists in HBM); it also emits the per-row logsumexp.  The
backward recomputes the probability blocks from (q, k, lse) and fuses
dq / dk / dv into two kernels with the same streaming structure — no O(T²)
residuals, so T=8192 training fits where the reference formula would not.
Off-TPU the kernels run interpreted.

Layout notes (see /opt/skills/guides/pallas_guide.md): per-row statistics
(m, l) are kept as [block_q, 128] row-constant tiles so every elementwise op
is lane-aligned; the two matmuls per block ride the MXU in float32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -1e30
_LANES = 128
# Per-row statistics (lse, delta) are stored [bh, t, 8]: a block's last two
# dims must be (8·k, 128·k) or span the array, and 8 lanes is the cheapest
# layout that qualifies while keeping rows on sublanes (no transpose).
_ROW_LANES = 8


def reference_attention(
    q, k, v, causal: bool = True, segments=None, window: int = 0
):
    """O(T²) oracle.  Supports grouped-query attention: k/v may carry
    fewer heads than q (H % KVH == 0); they are broadcast per group.
    ``segments`` [B, T] int restricts attention to same-segment pairs
    (sequence packing); ``window`` > 0 restricts each query to the last
    ``window`` positions (sliding-window attention, causal only)."""
    d = q.shape[-1]
    if k.shape[2] != q.shape[2]:
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / (d**0.5)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        if window:
            mask &= (
                jnp.arange(tq)[:, None] - jnp.arange(tk)[None, :] < window
            )
        scores = jnp.where(mask, scores, _NEG_BIG)
    elif window:
        raise ValueError("sliding window requires causal attention")
    if segments is not None:
        same = segments[:, :, None] == segments[:, None, :]  # [B, Tq, Tk]
        scores = jnp.where(same[:, None, :, :], scores, _NEG_BIG)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(
        q.dtype
    )


def _lanes(x, n):
    """Row-constant [rows, 128] statistic → [rows, n] (any lane has the value)."""
    if n <= _LANES:
        return x[:, :n]
    assert n % _LANES == 0
    return pltpu.repeat(x, n // _LANES, axis=1)


def _causal_mask(scores, qi, ki, block_q, block_k, window=0):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1
    )
    keep = q_pos >= k_pos
    if window:
        keep &= q_pos - k_pos < window
    return jnp.where(keep, scores, _NEG_BIG)


def _segment_mask(scores, segq_ref, segk_ref):
    """Mask cross-segment pairs (sequence packing).  seg_q rides a
    [bq, 8] row tile, seg_k a transposed [8, bk] lane tile; their
    [bq,1]==[1,bk] comparison broadcasts to the score block."""
    seg_q = segq_ref[0][:, :1]          # [bq, 1]
    seg_k = segk_ref[0][:1, :]          # [1, bk]
    return jnp.where(seg_q == seg_k, scores, _NEG_BIG)


def _fwd_kernel(
    q_ref, k_ref, v_ref, *rest,
    causal, scale, block_q, block_k, segmented=False, window=0,
):
    if segmented:
        segq_ref, segk_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    qi, ki = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)
    d = q_ref.shape[-1]

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Blocks strictly above the causal diagonal contribute nothing: skip
    # the compute (their DMA is wasted bandwidth but the MXU work
    # dominates).  A sliding window also skips blocks entirely BELOW it
    # (q_min - k_max >= window) — at T >> window this is where the
    # O(T·W) cost comes from.
    relevant = (
        ki * block_k <= qi * block_q + block_q - 1 if causal else ki >= 0
    )
    if causal and window:
        relevant &= qi * block_q - (ki * block_k + block_k - 1) < window

    @pl.when(relevant)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(  # q @ k.T on the MXU
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            scores = _causal_mask(scores, qi, ki, block_q, block_k, window)
        if segmented:
            scores = _segment_mask(scores, segq_ref, segk_ref)
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_curr = jnp.max(scores, axis=1, keepdims=True)  # [bq, 1]
        m_next = jnp.maximum(m_prev, m_curr)             # [bq, 128]
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - _lanes(m_next, scores.shape[1]))
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = m_next
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[...] = acc_scr[...] * _lanes(alpha, d) + pv

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / _lanes(l, d)).astype(o_ref.dtype)
        lse = m_scr[...] + jnp.log(l)          # [bq, 128] row-constant
        # lse rides a [bq, 8] row-constant tile: the narrowest lane width
        # the mosaic tiling rules allow without a sublane↔lane transpose.
        lse_ref[0] = lse[:, :_ROW_LANES]


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    causal, scale, block_q, block_k, segmented=False, window=0,
):
    if segmented:
        segq_ref, segk_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
    qi, ki = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    relevant = (
        ki * block_k <= qi * block_q + block_q - 1 if causal else ki >= 0
    )
    if causal and window:
        relevant &= qi * block_q - (ki * block_k + block_k - 1) < window

    @pl.when(relevant)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]       # [bq, 1] per-row, sublane-aligned
        delta = delta_ref[0][:, :1]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            scores = _causal_mask(scores, qi, ki, block_q, block_k, window)
        if segmented:
            scores = _segment_mask(scores, segq_ref, segk_ref)
        p = jnp.exp(scores - lse)                 # recomputed prob block
        dp = jax.lax.dot_general(                 # do @ v.T
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dq_scr[...] += scale * jax.lax.dot_general(  # ds @ k
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    causal, scale, block_q, block_k, n_q, segmented=False, window=0,
):
    if segmented:
        segq_ref, segk_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
    # Grid: (b·kvh, n_k, group·n_q) — the innermost dim walks every
    # (q-head-in-group, q-block) pair so each kv-head's dk/dv output block
    # is visited contiguously (GQA: several q heads accumulate into one
    # kv head; a non-contiguous revisit would flush the block early).
    ki, j = pl.program_id(1), pl.program_id(2)
    n_j = pl.num_programs(2)
    qi = j % n_q

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    relevant = (
        qi * block_q + block_q - 1 >= ki * block_k if causal else j >= 0
    )
    if causal and window:
        relevant &= qi * block_q - (ki * block_k + block_k - 1) < window

    @pl.when(relevant)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]       # [bq, 1] per-row, sublane-aligned
        delta = delta_ref[0][:, :1]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            scores = _causal_mask(scores, qi, ki, block_q, block_k, window)
        if segmented:
            scores = _segment_mask(scores, segq_ref, segk_ref)
        p = jnp.exp(scores - lse)
        dv_scr[...] += jax.lax.dot_general(       # p.T @ do
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        # dk = scale·dsᵀ@q_raw; q here is already q_raw·scale, so no rescale.
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == n_j - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _heads_first(x):
    """[B, T, H, D] → [B*H, T, D] so each grid row owns one head."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _heads_last(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _interpret():
    return jax.default_backend() != "tpu"


def _auto_block(t: int, want: int):
    """Largest power-of-two block ≤ ``want`` that divides t (≥128)."""
    b = want
    while b >= 128:
        if b <= t and t % b == 0:
            return b
        b //= 2
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q, k, v, causal: bool = True, block_q: int = 0, block_k: int = 0,
    window: int = 0, segments=None,
):
    """Attention over [B, T, H, D] with blockwise online softmax.

    ``block_q``/``block_k`` of 0 auto-tune: measured on v5e, (512, 1024)
    blocks are ~6x faster than (128, 128) at T=8192 (bigger tiles amortize
    the per-block DMA + relayout overhead; VMEM still fits comfortably).
    ``window`` > 0 is sliding-window attention (causal only): each query
    sees the last ``window`` keys, and blocks fully below the window are
    SKIPPED — O(T·W) compute instead of O(T²/2).
    ``segments`` [B, T] int masks attention to same-segment pairs
    (sequence packing); it rides the kernels as [*, 8]-lane tiles.
    """
    out, _ = _forward(q, k, v, causal, block_q, block_k, window, segments)
    return out


def _resolve_blocks(t: int, block_q: int, block_k: int):
    """(block_q, block_k) with 0 → auto, or None when the kernel can't tile t."""
    block_q = block_q or _auto_block(t, 512) or 1
    block_k = block_k or _auto_block(t, 1024) or 1
    if t % block_q or t % block_k or block_q < 8 or block_k < 128:
        return None
    return block_q, block_k


def _gqa_group(q, k):
    """q-heads per kv-head (grouped-query attention; 1 = classic MHA)."""
    h, kvh = q.shape[2], k.shape[2]
    if h % kvh:
        raise ValueError(f"n_heads {h} not divisible by n_kv_heads {kvh}")
    return h // kvh


def _kv_row_map(h: int, kvh: int):
    """Grid-row map q-head row → kv-head row ([b, h] row-major → [b, kvh]
    row-major): THE one definition both forward and backward index maps
    use, so their kv addressing can never desynchronize."""
    group = h // kvh
    return lambda g: (g // h) * kvh + (g % h) // group


def _seg_tiles(segments):
    """[B, T] segment ids → (row tile [B, T, 8], lane tile [B, 8, T])."""
    seg = segments.astype(jnp.int32)
    b, t = seg.shape
    rows = jnp.broadcast_to(seg[:, :, None], (b, t, _ROW_LANES))
    cols = jnp.broadcast_to(seg[:, None, :], (b, _ROW_LANES, t))
    return rows, cols


def _forward(q, k, v, causal, block_q, block_k, window=0, segments=None):
    b, t, h, d = q.shape
    group = _gqa_group(q, k)
    blocks = _resolve_blocks(t, block_q, block_k)
    if window and not causal:
        raise ValueError("sliding window requires causal attention")
    if blocks is None:
        # Ragged tails: fall back to the reference (bench shapes are
        # block-aligned; correctness everywhere beats a padded kernel).
        return (
            reference_attention(q, k, v, causal, segments, window), None
        )
    block_q, block_k = blocks
    scale = 1.0 / (d**0.5)
    qh, kh, vh = _heads_first(q), _heads_first(k), _heads_first(v)
    bh = b * h
    # The kv index map folds the GQA grouping: q-head row g reads kv-head
    # row g // group (per batch: rows are [b, h] row-major, so the batch
    # offset rescales from h-strides to kvh-strides).
    kv_row = _kv_row_map(h, h // group)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda g, qi, ki: (g, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda g, qi, ki: (kv_row(g), ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda g, qi, ki: (kv_row(g), ki, 0)),
    ]
    operands = [qh, kh, vh]
    if segments is not None:
        seg_rows, seg_cols = _seg_tiles(segments)
        in_specs += [
            pl.BlockSpec(
                (1, block_q, _ROW_LANES), lambda g, qi, ki: (g // h, qi, 0)
            ),
            pl.BlockSpec(
                (1, _ROW_LANES, block_k), lambda g, qi, ki: (g // h, 0, ki)
            ),
        ]
        operands += [seg_rows, seg_cols]
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k,
            segmented=segments is not None, window=window,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, _ROW_LANES), jnp.float32),
        ],
        grid=(bh, t // block_q, t // block_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda g, qi, ki: (g, qi, 0)),
            pl.BlockSpec(
                (1, block_q, _ROW_LANES), lambda g, qi, ki: (g, qi, 0)
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    return _heads_last(out, b, h), lse


def _fwd(q, k, v, causal, block_q, block_k, window=0, segments=None):
    out, lse = _forward(q, k, v, causal, block_q, block_k, window, segments)
    return out, (q, k, v, out, lse, segments)


def _seg_grad(segments):
    """float0 cotangent for the (integer) segment ids."""
    if segments is None:
        return None
    import numpy as np

    return np.zeros(segments.shape, jax.dtypes.float0)


def _bwd(causal, block_q, block_k, window, residuals, g):
    q, k, v, out, lse, segments = residuals
    if lse is None:  # ragged forward fell back to the reference formula
        _, vjp = jax.vjp(
            lambda q, k, v: reference_attention(
                q, k, v, causal, segments, window
            ),
            q, k, v,
        )
        return (*vjp(g), _seg_grad(segments))
    b, t, h, d = q.shape
    kvh = k.shape[2]
    group = h // kvh
    block_q, block_k = _resolve_blocks(t, block_q, block_k)
    bh = b * h
    scale = 1.0 / (d**0.5)
    n_q, n_k = t // block_q, t // block_k
    qh, kh, vh = _heads_first(q), _heads_first(k), _heads_first(v)
    doh = _heads_first(g)
    # delta_i = Σ_d dO·O per row — the softmax-normalization term of dS.
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1).reshape(bh, t)
    delta = jnp.broadcast_to(delta[..., None], (bh, t, _ROW_LANES))

    common = dict(
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        window=window,
    )
    # GQA: q-head row g reads kv-head row kv_row(g) (group size 1 = MHA).
    kv_row = _kv_row_map(h, kvh)
    qspec = pl.BlockSpec((1, block_q, d), lambda g_, qi, ki: (g_, qi, 0))
    kspec = pl.BlockSpec(
        (1, block_k, d), lambda g_, qi, ki: (kv_row(g_), ki, 0)
    )
    rowspec = pl.BlockSpec(
        (1, block_q, _ROW_LANES), lambda g_, qi, ki: (g_, qi, 0)
    )
    segmented = segments is not None
    dq_specs = [qspec, kspec, kspec, qspec, rowspec, rowspec]
    dq_operands = [qh, kh, vh, doh, lse, delta]
    if segmented:
        seg_rows, seg_cols = _seg_tiles(segments)
        dq_specs += [
            pl.BlockSpec(
                (1, block_q, _ROW_LANES), lambda g_, qi, ki: (g_ // h, qi, 0)
            ),
            pl.BlockSpec(
                (1, _ROW_LANES, block_k), lambda g_, qi, ki: (g_ // h, 0, ki)
            ),
        ]
        dq_operands += [seg_rows, seg_cols]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, segmented=segmented, **common),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=(bh, n_q, n_k),
        in_specs=dq_specs,
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*dq_operands)

    # dk/dv accumulate per kv head over every (q-head-in-group, q-block):
    # grid rows are kv heads; the innermost dim j walks group·n_q pairs so
    # the output block (g, ki) is visited contiguously.
    q_row = lambda g_, j: (g_ // kvh) * h + (g_ % kvh) * group + j // n_q  # noqa: E731
    qspec2 = pl.BlockSpec(
        (1, block_q, d), lambda g_, ki, j: (q_row(g_, j), j % n_q, 0)
    )
    kspec2 = pl.BlockSpec((1, block_k, d), lambda g_, ki, j: (g_, ki, 0))
    rowspec2 = pl.BlockSpec(
        (1, block_q, _ROW_LANES),
        lambda g_, ki, j: (q_row(g_, j), j % n_q, 0),
    )
    dkv_specs = [qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2]
    dkv_operands = [qh, kh, vh, doh, lse, delta]
    if segmented:
        dkv_specs += [
            pl.BlockSpec(
                (1, block_q, _ROW_LANES),
                lambda g_, ki, j: (q_row(g_, j) // h, j % n_q, 0),
            ),
            pl.BlockSpec(
                (1, _ROW_LANES, block_k), lambda g_, ki, j: (g_ // kvh, 0, ki)
            ),
        ]
        dkv_operands += [seg_rows, seg_cols]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, n_q=n_q, segmented=segmented, **common),
        out_shape=[
            jax.ShapeDtypeStruct((b * kvh, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * kvh, t, d), v.dtype),
        ],
        grid=(b * kvh, n_k, group * n_q),
        in_specs=dkv_specs,
        out_specs=[kspec2, kspec2],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*dkv_operands)
    return (
        _heads_last(dq, b, h),
        _heads_last(dk, b, kvh),
        _heads_last(dv, b, kvh),
        _seg_grad(segments),
    )


flash_attention.defvjp(_fwd, _bwd)
