"""Flash attention (pallas): blockwise causal attention, O(T) memory.

Forward is a pallas kernel — per (batch·head, q-block) grid step the q block
sits in VMEM while k/v stream through in blocks with the online-softmax
running max/denominator, so the [T, T] score matrix never materializes in
HBM and the two einsums per block ride the MXU.  Backward recomputes via the
reference formula under ``jax.custom_vjp`` (correct; a fused backward kernel
is a planned optimization).  Off-TPU the kernel runs interpreted.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_BIG = -1e30


def reference_attention(q, k, v, causal: bool = True):
    d = q.shape[-1]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / (d**0.5)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(mask, scores, _NEG_BIG)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(
        q.dtype
    )


def _kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale):
    # q_ref: [1, block_q, D]; k_ref/v_ref: [1, T, D] (this head's full K/V
    # in VMEM); o_ref: [1, block_q, D].  Grid: (B*H, T // block_q).
    q_block_idx = pl.program_id(1)
    _, block_q, d = q_ref.shape
    t = k_ref.shape[1]
    n_k_blocks = t // block_k
    q = q_ref[0].astype(jnp.float32) * scale
    q_pos = q_block_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        scores = q @ k_blk.T  # [block_q, block_k] on the MXU
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            scores = jnp.where(q_pos >= k_pos, scores, _NEG_BIG)
        block_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, block_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[:, None])
        new_l = l * correction + jnp.sum(p, axis=-1)
        new_acc = acc * correction[:, None] + p @ v_blk
        return new_m, new_l, new_acc

    m0 = jnp.full((block_q,), _NEG_BIG, dtype=jnp.float32)
    l0 = jnp.zeros((block_q,), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, d), dtype=jnp.float32)
    if causal:
        # Blocks strictly above the diagonal contribute nothing; bound the
        # loop at the q block's last row.
        upper = jnp.minimum(
            (q_block_idx + 1) * block_q + block_k - 1, t
        ) // block_k
    else:
        upper = n_k_blocks
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q, k, v, causal: bool = True, block_q: int = 128, block_k: int = 128
):
    """Attention over [B, T, H, D] with blockwise online softmax."""
    return _forward(q, k, v, causal, block_q, block_k)


def _forward(q, k, v, causal, block_q, block_k):
    b, t, h, d = q.shape
    if t % block_q or t % block_k:
        # Ragged tails: fall back to the reference (bench shapes are
        # block-aligned; correctness everywhere beats a padded kernel).
        return reference_attention(q, k, v, causal)
    scale = 1.0 / (d**0.5)
    # [B, T, H, D] -> [B*H, T, D] so each grid row owns one head.
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    out = pl.pallas_call(
        functools.partial(
            _kernel, block_k=block_k, causal=causal, scale=scale
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        interpret=jax.default_backend() != "tpu",
    )(qh, kh, vh)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _fwd(q, k, v, causal, block_q, block_k):
    return _forward(q, k, v, causal, block_q, block_k), (q, k, v)


def _bwd(causal, block_q, block_k, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q, k, v: reference_attention(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
