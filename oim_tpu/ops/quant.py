"""Int8 quantization for the KV cache (plain JAX — XLA fuses the
dequantizing convert+multiply into the attention matmul's operand read).

Decode is cache-bandwidth-bound (doc/compute.md), so shrinking cache
bytes is a direct throughput lever, multiplicative with GQA's kv-head
reduction.  Scheme: symmetric per-(token, head) max-abs scaling — one
f32 scale (4 bytes) per stored [head_dim] int8 vector, so at head_dim
64 the cache is 68 bytes per vector vs 128 for bf16 (0.53×; the scale
is a 1/16 byte overhead on the int8 payload).  New work for the TPU
build (the reference is a storage control plane; SURVEY.md §2.3).
"""

from __future__ import annotations

import jax.numpy as jnp

# Symmetric int8 range; -128 is unused so the scale inverts exactly.
_INT8_MAX = 127.0
_EPS = 1e-8


def quantize_int8(x):
    """[..., d] float → (int8 values [..., d], f32 scales [...]).

    Per-vector symmetric max-abs: scale = amax/127, q = round(x/scale).
    A zero vector quantizes to zeros with a tiny scale (no NaN).
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / _INT8_MAX, _EPS)
    q = jnp.round(xf / scale[..., None]).astype(jnp.int8)
    return q, scale


def dequantize_int8(q, scale):
    """Inverse of ``quantize_int8``: int8 [..., d] × f32 scales [...] →
    f32 [..., d]."""
    return q.astype(jnp.float32) * scale[..., None]


def make_kv_buffers(shape, compute_dtype, quantized: bool):
    """Zeroed (k, v, k_scale, v_scale) cache buffers for ``shape``
    [..., max_len, kv_heads, head_dim] — THE one definition of the
    quantized-cache layout, shared by the solo decode cache and the
    serving slot cache so the two can never diverge.

    Scales are distinct arrays (aliasing one buffer into both fields
    breaks jit donation: "donate the same buffer twice") and None when
    not quantized (an empty pytree — scan/tree.map pass it through).
    """
    dt = jnp.int8 if quantized else compute_dtype
    mk_scale = lambda: (  # noqa: E731
        jnp.ones(shape[:-1], jnp.float32) if quantized else None
    )
    return (
        jnp.zeros(shape, dt),
        jnp.zeros(shape, dt),
        mk_scale(),
        mk_scale(),
    )
