"""Int8 quantization for the KV cache (plain JAX — XLA fuses the
dequantizing convert+multiply into the attention matmul's operand read).

Decode is cache-bandwidth-bound (doc/compute.md), so shrinking cache
bytes is a direct throughput lever, multiplicative with GQA's kv-head
reduction.  Scheme: symmetric per-(token, head) max-abs scaling — one
f32 scale (4 bytes) per stored [head_dim] int8 vector, so at head_dim
64 the cache is 68 bytes per vector vs 128 for bf16 (0.53×; the scale
is a 1/16 byte overhead on the int8 payload).  New work for the TPU
build (the reference is a storage control plane; SURVEY.md §2.3).
"""

from __future__ import annotations

import jax.numpy as jnp

# Symmetric int8 range; -128 is unused so the scale inverts exactly.
_INT8_MAX = 127.0
_EPS = 1e-8


def quantize_int8(x):
    """[..., d] float → (int8 values [..., d], f32 scales [...]).

    Per-vector symmetric max-abs: scale = amax/127, q = round(x/scale).
    A zero vector quantizes to zeros with a tiny scale (no NaN).
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / _INT8_MAX, _EPS)
    q = jnp.round(xf / scale[..., None]).astype(jnp.int8)
    return q, scale


def dequantize_int8(q, scale):
    """Inverse of ``quantize_int8``: int8 [..., d] × f32 scales [...] →
    f32 [..., d]."""
    return q.astype(jnp.float32) * scale[..., None]


def quantize_weight_int8(w):
    """Weight-only int8: symmetric per-output-channel max-abs over the
    reduction (second-to-last) axis.  ``w [..., din, dout]`` →
    (int8 [..., din, dout], f32 scales [..., dout]); dequantize with
    ``q.astype(f32) * scale[..., None, :]``."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2)
    scale = jnp.maximum(amax / _INT8_MAX, _EPS)
    q = jnp.round(wf / scale[..., None, :]).astype(jnp.int8)
    return q, scale


def dequantize_weight_int8(q, scale):
    """Inverse of ``quantize_weight_int8`` (XLA fuses the convert+scale
    into the consuming matmul's operand read — HBM traffic stays int8)."""
    return q.astype(jnp.float32) * scale[..., None, :]


# Symmetric int4 range; -8 unused so the scale inverts exactly.
_INT4_MAX = 7.0


def quantize_int4(x):
    """[..., d] float → (int4 values [..., d], f32 scales [...]) — the
    KV-cache int4 scheme (``--kv-int4``): per-vector symmetric max-abs,
    exactly ``quantize_int8`` with the int4 range.  Dequantize with
    ``dequantize_int8`` (it only does ``astype(f32) * scale``, so the
    payload dtype is free to be int4) — one dequant definition for the
    whole KV quant ladder.  Paged-pool only: dense layouts reject int4
    KV because only the block pool carries the per-block scale arrays
    the fused kernel gathers (``ops/paged_attention.py``)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / _INT4_MAX, _EPS)
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -_INT4_MAX, _INT4_MAX
    ).astype(jnp.int4)
    return q, scale


def _int4_group(din: int, group: int) -> int:
    """Effective group size: the largest divisor of ``din`` ≤ the
    requested group (gcd), so any layer geometry quantizes — a d_ff not
    divisible by the requested group degrades to a finer group, never
    an error at serve time."""
    import math

    return max(1, math.gcd(din, group))


def quantize_weight_int4(w, group: int = 64):
    """Weight-only int4 with GROUP-WISE scales along the reduction axis:
    ``w [..., din, dout]`` → (int4 [..., din, dout], f32 scales
    [..., din/g, dout]).  Per-channel int4 loses too much range on real
    weight distributions; a g-row group keeps the max-abs local.  HBM
    cost: 0.5 bytes/weight + 4/g bytes of scale (≈0.56 at g=64) vs
    int8's ~1.03 — decode is weight-bandwidth-bound at small batch once
    GQA+int8 shrink the KV cache, so this is the next decode lever
    (BASELINE.md decode rows; measured by tools/decode_bench.py)."""
    wf = w.astype(jnp.float32)
    din = wf.shape[-2]
    g = _int4_group(din, group)
    grouped = wf.reshape(*wf.shape[:-2], din // g, g, wf.shape[-1])
    amax = jnp.max(jnp.abs(grouped), axis=-2)
    scale = jnp.maximum(amax / _INT4_MAX, _EPS)
    q = jnp.clip(
        jnp.round(grouped / scale[..., None, :]), -_INT4_MAX, _INT4_MAX
    ).astype(jnp.int4)
    return q.reshape(wf.shape), scale


def dequantize_weight_int4(q, scale):
    """Inverse of ``quantize_weight_int4`` (XLA keeps the int4 operand
    packed in HBM on TPU and fuses convert+scale into the matmul read)."""
    din = q.shape[-2]
    n_groups = scale.shape[-2]
    g = din // n_groups
    grouped = q.astype(jnp.float32).reshape(
        *q.shape[:-2], n_groups, g, q.shape[-1]
    )
    return (grouped * scale[..., None, :]).reshape(q.shape)


WEIGHT_QUANT_TARGETS = (
    "wq", "wk", "wv", "wo", "w_gate", "w_in", "w_out", "wlm",
)


def quantize_params_int8(params: dict) -> dict:
    """Weight-only int8 for inference params: each matmul weight in
    ``WEIGHT_QUANT_TARGETS`` becomes int8 with a ``<name>_wscale``
    companion (per-output-channel, ``quantize_weight_int8``).  The
    embedding (a gather, not a matmul), norms, and the MoE router (small,
    deliberately f32) pass through.  Inference-only: the training path
    never sees quantized params."""
    out = {}
    for name, value in params.items():
        if name in WEIGHT_QUANT_TARGETS:
            q, scale = quantize_weight_int8(value)
            out[name] = q
            out[f"{name}_wscale"] = scale
        else:
            out[name] = value
    return out


def quantize_params_int4(params: dict, group: int = 64) -> dict:
    """Weight-only int4 (group-wise) for inference params — the int8
    scheme's shape (``quantize_params_int8``) with int4 payloads; the
    VALUE dtype selects the dequant path, so the ``_wscale`` companion
    rule and every consumer stay unchanged."""
    out = {}
    for name, value in params.items():
        if name in WEIGHT_QUANT_TARGETS:
            q, scale = quantize_weight_int4(value, group)
            out[name] = q
            out[f"{name}_wscale"] = scale
        else:
            out[name] = value
    return out


def dequantize_named(tree: dict, name: str, dtype=None):
    """``tree[name]`` dequantized iff its ``_wscale`` companion exists —
    THE one definition of the companion-key rule, used by the layer path
    (via ``maybe_dequantize_weights``) and the unembedding alike.  The
    value's dtype selects the scheme: int4 payloads carry group-wise
    scales, int8 per-output-channel.

    ``dtype`` casts the dequantized weight (pass the compute dtype: a
    f32 operand against bf16 activations would promote the matmul to
    half MXU rate — the same discipline train's _cast_matmul_weights
    keeps for master weights)."""
    value = tree[name]
    scale = tree.get(f"{name}_wscale")
    if scale is None:
        return value
    if value.dtype == jnp.int4:
        deq = dequantize_weight_int4(value, scale)
    else:
        deq = dequantize_weight_int8(value, scale)
    return deq if dtype is None else deq.astype(dtype)


def weight_quant_mode(params: dict) -> str:
    """'' (unquantized) | 'int8' | 'int4' — decided by the payload dtype
    of any scaled weight, same dispatch as ``dequantize_named``."""
    for name in params:
        if name.endswith("_wscale"):
            value = params[name[: -len("_wscale")]]
            return "int4" if value.dtype == jnp.int4 else "int8"
    return ""


def maybe_dequantize_weights(tree: dict, dtype=None) -> dict:
    """Undo ``quantize_params_int8`` on any dict holding quantized
    weights (full params or a per-layer slice); everything else passes
    through.  A no-op (same dict) on unquantized trees.  ``dtype`` as in
    ``dequantize_named``."""
    if not any(name.endswith("_wscale") for name in tree):
        return tree
    return {
        name: dequantize_named(tree, name, dtype)
        for name in tree
        if not name.endswith("_wscale")
    }


def make_kv_buffers(shape, compute_dtype, quantized):
    """Zeroed (k, v, k_scale, v_scale) cache buffers for ``shape``
    [..., max_len, kv_heads, head_dim] — THE one definition of the
    quantized-cache layout, shared by the solo decode cache and the
    serving slot cache so the two can never diverge.

    ``quantized`` is the KV quant mode: falsy = full precision, truthy
    (``True``/``"int8"``) = int8, ``"int4"`` = int4 payloads (the kv4
    rung of the ladder; same f32 per-(token, head) scale arrays — the
    payload dtype alone selects the scheme everywhere downstream).

    Scales are distinct arrays (aliasing one buffer into both fields
    breaks jit donation: "donate the same buffer twice") and None when
    not quantized (an empty pytree — scan/tree.map pass it through).
    """
    dt = (
        jnp.int4 if quantized == "int4"
        else jnp.int8 if quantized
        else compute_dtype
    )
    mk_scale = lambda: (  # noqa: E731
        jnp.ones(shape[:-1], jnp.float32) if quantized else None
    )
    return (
        jnp.zeros(shape, dt),
        jnp.zeros(shape, dt),
        mk_scale(),
        mk_scale(),
    )
