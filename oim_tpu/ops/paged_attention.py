"""Paged flash-decode (pallas): attention straight off the block pool.

The gather path (``ops/paged.py``) pays an extra HBM round-trip per
layer per decode chunk: ``paged_view`` materializes every slot's blocks
into a dense [B, max_len, KVH, hd] region that the shared attention
code then reads AGAIN.  Decode is cache-bandwidth-bound
(doc/compute.md), so on the TPU that doubles the dominant cost of the
step.  This kernel is the vLLM-PagedAttention move fused with the
FlashAttention online-softmax tiling already proven by the
training-side kernel (``ops/flash_attention.py``, 6.3x vs unfused per
BENCH_LAST_GOOD): the grid tiles over **(slot, kv-head, block)** and
each step DMAs ONE pool block into VMEM through the per-slot block
table — K/V bytes cross HBM exactly once, there is no dense
intermediate, and the [max_len] score row never exists in memory.

Contract (mirrors the gather path bit-for-bit where floating point
allows, token-identically where it does not):

- **Block table indirection in the index map.**  ``tables`` [B,
  n_tables] rides scalar prefetch; the K/V BlockSpec index maps read
  ``tables[b, j]`` to pick which pool block grid step (b, h, j) DMAs.
  Sentinel entries (``n_blocks`` — padding admissions, freed slots)
  contribute **nothing**: the whole compute body is predicated off, so
  a freed-and-reallocated block is never read at all — the symmetric
  (and strictly stronger) form of the gather path's sentinel-clamp
  invariant (``ops/paged.py`` module docstring; the index map still
  clamps to ``n_blocks - 1`` so the prefetched DMA address stays in
  range, but the fetched bytes are dead).
- **Online softmax across a slot's blocks.**  The innermost grid
  dimension walks the table sequentially; running max / denominator /
  accumulator live in VMEM scratch, exactly the forward flash kernel's
  scheme.  The ``-1e30`` mask constant and the ``scores / sqrt(hd)``
  scaling reproduce the gather path's arithmetic so the two paths are
  token-identical across the serve exactness matrix
  (tests/test_serve_paged.py pins kernel == gather == dense oracle).
- **GQA head grouping.**  Grid rows are KV heads; the q operand is
  pre-folded to [B, KVH, t·group, hd] so one block read serves every
  query head in the group — same ratio of K/V traffic to q heads as
  the training kernel's ``_kv_row_map``.
- **Fused dequant at the operand read.**  int8 AND int4 pools
  dequantize inside the kernel (``astype(f32) * scale``, the
  ``_load_kv`` formula, scales gathered per block through the same
  index map) — HBM traffic stays the quantized payload.  kv4 halves
  int8's cache bytes again; it exists only on the paged layout because
  only the pool carries the block-structured scale arrays this kernel
  gathers (dense engines reject ``kv_int4`` at construction).
- **Interpret off-TPU** (the ``_interpret()`` pattern), so the whole
  exactness matrix runs in tier-1 on CPU; the HBM win is claimed by
  the TPU bench rows (doc/operations.md "CPU-backend caveat").

Decode-only by design: admission prefill keeps the gather (prefill is
compute-bound — the dense intermediate it materializes is the bytes the
MXU was going to stream anyway), which also keeps this kernel's q tile
small ([t·group, hd], t = 1 or spec_decode+1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# One definition of the lane tiling, mask constant, and off-TPU
# interpret policy for BOTH flash kernels — a divergence here would be
# a silent numerics split between training and serving attention.
from oim_tpu.ops.flash_attention import _LANES, _NEG_BIG, _interpret, _lanes


def supported_block_size(block_size: int, head_dim: int) -> bool:
    """Whether the kernel's lane tiling covers this geometry: the
    ``_lanes`` broadcast needs each of block_size and head_dim to be
    ≤ 128 or a multiple of 128.  The engine checks this at
    construction (a clear ValueError beats an AssertionError out of
    the first decode trace); the gather path has no such constraint."""
    return all(n <= _LANES or n % _LANES == 0 for n in (block_size, head_dim))


def _decode_kernel(
    tables_ref, starts_ref, q_ref, k_ref, v_ref, *rest,
    block_size, n_blocks, group, window, quantized,
):
    """One grid step = one (slot b, kv-head h, table entry j): fold
    pool block ``tables[b, j]`` into row b's online softmax.  Scratch
    (m, l, acc) persists across j — the innermost, sequential grid
    dimension — and the output block is written at the last j."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    b, j = pl.program_id(0), pl.program_id(2)
    n_j = pl.num_programs(2)
    hd = q_ref.shape[-1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Sentinel entries contribute NOTHING (the OOB-drop contract of
    # paged_store, upheld on the read side): the block's bytes were
    # DMA'd (clamped index — the prefetch address must be in range)
    # but the compute never touches them, so a freed-and-reallocated
    # block cannot leak into this row even transiently.
    @pl.when(tables_ref[b, j] < n_blocks)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [t·G, hd]
        k = k_ref[0, :, 0, :].astype(jnp.float32)    # [bs, hd]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            # The _load_kv dequant formula (astype · scale), applied at
            # the operand read — int8 and int4 payloads alike, so HBM
            # carried only the quantized bytes.
            k = k * ks_ref[0, :, 0][:, None]
            v = v * vs_ref[0, :, 0][:, None]
        scores = jax.lax.dot_general(  # q @ k.T on the MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / (hd ** 0.5)
        # Causal per slot, identical position arithmetic to the gather
        # path: query row r (= position index r // group within the
        # chunk) sits at global position starts[b] + r // group; block
        # j's columns are global positions j·bs .. j·bs + bs - 1.
        q_pos = starts_ref[b] + (
            jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0) // group
        )
        k_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1
        )
        keep = k_pos <= q_pos
        if window:
            keep &= q_pos - k_pos < window
        scores = jnp.where(keep, scores, _NEG_BIG)
        # Online softmax: an all-masked block transiently contributes
        # exp(0) rows, annihilated exactly (alpha == 0.0) when the
        # first real score arrives — the flash forward's property.
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_curr = jnp.max(scores, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_curr)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - _lanes(m_next, scores.shape[1]))
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = m_next
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = acc_scr[...] * _lanes(alpha, hd) + pv

    @pl.when(j == n_j - 1)
    def _finalize():
        # A row with NO valid block (all-sentinel table: an inactive
        # slot) has l == 0: clamp and emit zeros — garbage the host
        # never reads, like the gather path's uniform-garbage rows.
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0, 0] = acc_scr[...] / _lanes(l, hd)


# oimlint: hotpath
def paged_flash_decode(
    q, k_pool, v_pool, k_scale, v_scale, tables, starts, *, window: int = 0
):
    """Attention for decode-sized q straight off the paged pool.

    q: [B, t, H, hd] (t small: 1 plain, spec_decode+1 verify);
    k_pool/v_pool: [n_blocks, block_size, KVH, hd] (fp, int8, or int4);
    k_scale/v_scale: [n_blocks, block_size, KVH] f32 or None;
    tables: [B, n_tables] int32, sentinel entry == n_blocks;
    starts: [B] int32 — q row i of slot b sits at global position
    ``starts[b] + i`` and attends rows ``<= that`` (minus ``window``).
    Returns [B, t, H, hd] float32 — the gather path's pre-``wo``
    attention output, position for position.

    One compile covers every block-table content (tables/starts are
    data, not trace constants); the caller keeps shapes static exactly
    as it does for the gather.
    """
    b, t, h, hd = q.shape
    n_blocks, block_size, kvh, _ = k_pool.shape
    n_tables = tables.shape[1]
    if h % kvh:
        raise ValueError(f"n_heads {h} not divisible by kv_heads {kvh}")
    if not supported_block_size(block_size, hd):
        raise ValueError(
            f"paged_flash_decode needs block_size and head_dim each "
            f"<= {_LANES} or a multiple of {_LANES} (the lane-tiling "
            f"constraint); got block_size={block_size}, head_dim={hd} "
            f"— use the gather path (paged_kernel=False) for this "
            f"geometry"
        )
    group = h // kvh
    tg = t * group
    quantized = k_scale is not None
    # Fold GQA into the row axis: [B, t, KVH, G, hd] → [B, KVH, t·G, hd]
    # so one (b, h) grid row reads its kv head's blocks once for every
    # query head in the group.
    qh = q.reshape(b, t, kvh, group, hd).transpose(0, 2, 1, 3, 4).reshape(
        b, kvh, tg, hd
    )

    def kv_map(b_, h_, j_, tables_ref, starts_ref):
        # The paged indirection lives HERE: entry j of slot b_ names
        # the pool block this grid step DMAs.  Clamped so a sentinel
        # still prefetches an in-range (dead) address; the kernel body
        # predicates its compute off instead.
        return (jnp.minimum(tables_ref[b_, j_], n_blocks - 1), 0, h_, 0)

    def scale_map(b_, h_, j_, tables_ref, starts_ref):
        return (jnp.minimum(tables_ref[b_, j_], n_blocks - 1), 0, h_)

    in_specs = [
        pl.BlockSpec((1, 1, tg, hd), lambda b_, h_, j_, *_: (b_, h_, 0, 0)),
        pl.BlockSpec((1, block_size, 1, hd), kv_map),
        pl.BlockSpec((1, block_size, 1, hd), kv_map),
    ]
    operands = [qh, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_size, 1), scale_map),
            pl.BlockSpec((1, block_size, 1), scale_map),
        ]
        operands += [k_scale, v_scale]
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel,
            block_size=block_size, n_blocks=n_blocks, group=group,
            window=window, quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, kvh, n_tables),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, tg, hd), lambda b_, h_, j_, *_: (b_, h_, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((tg, _LANES), jnp.float32),
                pltpu.VMEM((tg, _LANES), jnp.float32),
                pltpu.VMEM((tg, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kvh, tg, hd), jnp.float32),
        interpret=_interpret(),
    )(tables.astype(jnp.int32), starts.astype(jnp.int32), *operands)
    return out.reshape(b, kvh, t, group, hd).transpose(
        0, 2, 1, 3, 4
    ).reshape(b, t, h, hd)
