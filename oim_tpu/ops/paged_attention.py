"""Paged flash-decode (pallas): attention straight off the block pool.

The gather path (``ops/paged.py``) pays an extra HBM round-trip per
layer per decode chunk: ``paged_view`` materializes every slot's blocks
into a dense [B, max_len, KVH, hd] region that the shared attention
code then reads AGAIN.  Decode is cache-bandwidth-bound
(doc/compute.md), so on the TPU that doubles the dominant cost of the
step.  This kernel is the vLLM-PagedAttention move fused with the
FlashAttention online-softmax tiling already proven by the
training-side kernel (``ops/flash_attention.py``, 6.3x vs unfused per
BENCH_LAST_GOOD): the grid tiles over **(slot, kv-head, block)** and
each step DMAs ONE pool block into VMEM through the per-slot block
table — K/V bytes cross HBM exactly once, there is no dense
intermediate, and the [max_len] score row never exists in memory.

Contract (mirrors the gather path bit-for-bit where floating point
allows, token-identically where it does not):

- **Block table indirection in the index map.**  ``tables`` [B,
  n_tables] rides scalar prefetch; the K/V BlockSpec index maps read
  ``tables[b, j]`` to pick which pool block grid step (b, h, j) DMAs.
  Sentinel entries (``n_blocks`` — padding admissions, freed slots)
  contribute **nothing**: the whole compute body is predicated off, so
  a freed-and-reallocated block is never read at all — the symmetric
  (and strictly stronger) form of the gather path's sentinel-clamp
  invariant (``ops/paged.py`` module docstring; the index map still
  clamps to ``n_blocks - 1`` so the prefetched DMA address stays in
  range, but the fetched bytes are dead).
- **Online softmax across a slot's blocks.**  The innermost grid
  dimension walks the table sequentially; running max / denominator /
  accumulator live in VMEM scratch, exactly the forward flash kernel's
  scheme.  The ``-1e30`` mask constant and the ``scores / sqrt(hd)``
  scaling reproduce the gather path's arithmetic so the two paths are
  token-identical across the serve exactness matrix
  (tests/test_serve_paged.py pins kernel == gather == dense oracle).
- **GQA head grouping.**  Grid rows are KV heads; the q operand is
  pre-folded to [B, KVH, t·group, hd] so one block read serves every
  query head in the group — same ratio of K/V traffic to q heads as
  the training kernel's ``_kv_row_map``.
- **Fused dequant at the operand read.**  int8 AND int4 pools
  dequantize inside the kernel (``astype(f32) * scale``, the
  ``_load_kv`` formula, scales gathered per block through the same
  index map) — HBM traffic stays the quantized payload.  kv4 halves
  int8's cache bytes again; it exists only on the paged layout because
  only the pool carries the block-structured scale arrays this kernel
  gathers (dense engines reject ``kv_int4`` at construction).
- **Interpret off-TPU** (the ``_interpret()`` pattern), so the whole
  exactness matrix runs in tier-1 on CPU; the HBM win is claimed by
  the TPU bench rows (doc/operations.md "CPU-backend caveat").

Prefill rides the same machinery (ISSUE 20): ``paged_flash_prefill``
first STAGES a prompt segment's freshly-projected K/V into
block-granular merged buffers (``_prefill_stage`` below — fused
int8/int4 quant per the exact ``ops/quant.py`` formulas, straddle
blocks merged row-wise with the pool's current contents), lands them
through ``ops/paged.py::paged_store_blocks``'s sentinel-dropping
block scatter, then runs the SAME flash attend above over the updated
pool — its q-row path already handles arbitrary ``t`` (``q_pos =
starts[b] + i``), so a segment's causal prefill is just a tall decode.
Segment K/V bytes cross HBM once, quantized, with no dense
intermediate.  Staging never aliases the pool: an in-place aliased
write would let Mosaic's double-buffered input prefetch of a clamped
sentinel read race another grid step's live overlay of the same block
— the staged-buffers-plus-XLA-scatter split keeps every read-before-
write ordering explicit in the dataflow.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# One definition of the lane tiling, mask constant, and off-TPU
# interpret policy for BOTH flash kernels — a divergence here would be
# a silent numerics split between training and serving attention.
from oim_tpu.ops.flash_attention import _LANES, _NEG_BIG, _interpret, _lanes
from oim_tpu.ops.paged import paged_store_blocks


def supported_block_size(block_size: int, head_dim: int) -> bool:
    """Whether the kernel's lane tiling covers this geometry: the
    ``_lanes`` broadcast needs each of block_size and head_dim to be
    ≤ 128 or a multiple of 128.  The engine checks this at
    construction (a clear ValueError beats an AssertionError out of
    the first decode trace); the gather path has no such constraint."""
    return all(n <= _LANES or n % _LANES == 0 for n in (block_size, head_dim))


def _decode_kernel(
    tables_ref, starts_ref, q_ref, k_ref, v_ref, *rest,
    block_size, n_blocks, group, window, quantized,
):
    """One grid step = one (slot b, kv-head h, table entry j): fold
    pool block ``tables[b, j]`` into row b's online softmax.  Scratch
    (m, l, acc) persists across j — the innermost, sequential grid
    dimension — and the output block is written at the last j."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    b, j = pl.program_id(0), pl.program_id(2)
    n_j = pl.num_programs(2)
    hd = q_ref.shape[-1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Sentinel entries contribute NOTHING (the OOB-drop contract of
    # paged_store, upheld on the read side): the block's bytes were
    # DMA'd (clamped index — the prefetch address must be in range)
    # but the compute never touches them, so a freed-and-reallocated
    # block cannot leak into this row even transiently.
    @pl.when(tables_ref[b, j] < n_blocks)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [t·G, hd]
        k = k_ref[0, :, 0, :].astype(jnp.float32)    # [bs, hd]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            # The _load_kv dequant formula (astype · scale), applied at
            # the operand read — int8 and int4 payloads alike, so HBM
            # carried only the quantized bytes.
            k = k * ks_ref[0, :, 0][:, None]
            v = v * vs_ref[0, :, 0][:, None]
        scores = jax.lax.dot_general(  # q @ k.T on the MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / (hd ** 0.5)
        # Causal per slot, identical position arithmetic to the gather
        # path: query row r (= position index r // group within the
        # chunk) sits at global position starts[b] + r // group; block
        # j's columns are global positions j·bs .. j·bs + bs - 1.
        q_pos = starts_ref[b] + (
            jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0) // group
        )
        k_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1
        )
        keep = k_pos <= q_pos
        if window:
            keep &= q_pos - k_pos < window
        scores = jnp.where(keep, scores, _NEG_BIG)
        # Online softmax: an all-masked block transiently contributes
        # exp(0) rows, annihilated exactly (alpha == 0.0) when the
        # first real score arrives — the flash forward's property.
        m_prev, l_prev = m_scr[...], l_scr[...]
        m_curr = jnp.max(scores, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_curr)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - _lanes(m_next, scores.shape[1]))
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = m_next
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = acc_scr[...] * _lanes(alpha, hd) + pv

    @pl.when(j == n_j - 1)
    def _finalize():
        # A row with NO valid block (all-sentinel table: an inactive
        # slot) has l == 0: clamp and emit zeros — garbage the host
        # never reads, like the gather path's uniform-garbage rows.
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0, 0] = acc_scr[...] / _lanes(l, hd)


# oimlint: hotpath
def paged_flash_decode(
    q, k_pool, v_pool, k_scale, v_scale, tables, starts, *, window: int = 0
):
    """Attention for decode-sized q straight off the paged pool.

    q: [B, t, H, hd] (t small: 1 plain, spec_decode+1 verify);
    k_pool/v_pool: [n_blocks, block_size, KVH, hd] (fp, int8, or int4);
    k_scale/v_scale: [n_blocks, block_size, KVH] f32 or None;
    tables: [B, n_tables] int32, sentinel entry == n_blocks;
    starts: [B] int32 — q row i of slot b sits at global position
    ``starts[b] + i`` and attends rows ``<= that`` (minus ``window``).
    Returns [B, t, H, hd] float32 — the gather path's pre-``wo``
    attention output, position for position.

    One compile covers every block-table content (tables/starts are
    data, not trace constants); the caller keeps shapes static exactly
    as it does for the gather.
    """
    b, t, h, hd = q.shape
    n_blocks, block_size, kvh, _ = k_pool.shape
    n_tables = tables.shape[1]
    if h % kvh:
        raise ValueError(f"n_heads {h} not divisible by kv_heads {kvh}")
    if not supported_block_size(block_size, hd):
        raise ValueError(
            f"paged_flash_decode needs block_size and head_dim each "
            f"<= {_LANES} or a multiple of {_LANES} (the lane-tiling "
            f"constraint); got block_size={block_size}, head_dim={hd} "
            f"— use the gather path (paged_kernel=False) for this "
            f"geometry"
        )
    group = h // kvh
    tg = t * group
    quantized = k_scale is not None
    # Fold GQA into the row axis: [B, t, KVH, G, hd] → [B, KVH, t·G, hd]
    # so one (b, h) grid row reads its kv head's blocks once for every
    # query head in the group.
    qh = q.reshape(b, t, kvh, group, hd).transpose(0, 2, 1, 3, 4).reshape(
        b, kvh, tg, hd
    )

    def kv_map(b_, h_, j_, tables_ref, starts_ref):
        # The paged indirection lives HERE: entry j of slot b_ names
        # the pool block this grid step DMAs.  Clamped so a sentinel
        # still prefetches an in-range (dead) address; the kernel body
        # predicates its compute off instead.
        return (jnp.minimum(tables_ref[b_, j_], n_blocks - 1), 0, h_, 0)

    def scale_map(b_, h_, j_, tables_ref, starts_ref):
        return (jnp.minimum(tables_ref[b_, j_], n_blocks - 1), 0, h_)

    in_specs = [
        pl.BlockSpec((1, 1, tg, hd), lambda b_, h_, j_, *_: (b_, h_, 0, 0)),
        pl.BlockSpec((1, block_size, 1, hd), kv_map),
        pl.BlockSpec((1, block_size, 1, hd), kv_map),
    ]
    operands = [qh, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_size, 1), scale_map),
            pl.BlockSpec((1, block_size, 1), scale_map),
        ]
        operands += [k_scale, v_scale]
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel,
            block_size=block_size, n_blocks=n_blocks, group=group,
            window=window, quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, kvh, n_tables),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, tg, hd), lambda b_, h_, j_, *_: (b_, h_, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((tg, _LANES), jnp.float32),
                pltpu.VMEM((tg, _LANES), jnp.float32),
                pltpu.VMEM((tg, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kvh, tg, hd), jnp.float32),
        interpret=_interpret(),
    )(tables.astype(jnp.int32), starts.astype(jnp.int32), *operands)
    return out.reshape(b, kvh, t, group, hd).transpose(
        0, 2, 1, 3, 4
    ).reshape(b, t, h, hd)


def _prefill_stage_kernel(
    tables_ref, starts_ref, kn_ref, vn_ref, kp_ref, vp_ref, *rest,
    t, block_size, quantized, int4,
):
    """One grid step = one (slot b, window block jw): merge the rows of
    pool block ``starts[b] // block_size + jw`` that fall inside this
    row's write window ``[starts[b], starts[b] + t)`` — freshly
    projected, quantized in place — with the block's CURRENT contents
    everywhere else (the straddle rows a prior segment already wrote,
    and the not-yet-written tail), and emit the merged block to the
    staging output.  Rows are quantized independently (one scale per
    [position, kv-head] row, the ``paged_store`` granularity), so the
    row-wise merge is exact.  Blocks whose table entry is the sentinel
    stage clamped garbage that the landing scatter then DROPS — this
    kernel never needs its own sentinel predicate, only the pool's
    everything-stays-finite invariant (``ops/paged.py``)."""
    if quantized:
        ksp_ref, vsp_ref, ko_ref, vo_ref, kso_ref, vso_ref = rest
    else:
        ko_ref, vo_ref = rest
    b = pl.program_id(0)
    jw = pl.program_id(1)
    start = starts_ref[b]
    # Window-relative offset of this block's row 0.  The new-KV operand
    # is padded by one block on each side, so the dynamic slice below
    # stays in range for every straddle (o ∈ (-bs, t + bs]); rows the
    # slice pulls from the padding are masked off by ``inside``.
    o = (start // block_size + jw) * block_size - start
    s0 = jnp.minimum(o, t) + block_size
    kseg = kn_ref[0, pl.ds(s0, block_size)].astype(jnp.float32)
    vseg = vn_ref[0, pl.ds(s0, block_size)].astype(jnp.float32)
    rows = jax.lax.broadcasted_iota(jnp.int32, kseg.shape, 0)
    inside = ((o + rows) >= 0) & ((o + rows) < t)    # [bs, kvh, hd]
    kp = kp_ref[0].astype(jnp.float32)
    vp = vp_ref[0].astype(jnp.float32)
    if not quantized:
        # fp pool: the landing astype round-trips bf16 losslessly, so
        # keep-rows rewrite bit-identical bytes.
        ko_ref[0, 0] = jnp.where(inside, kseg, kp)
        vo_ref[0, 0] = jnp.where(inside, vseg, vp)
        return

    def quant(x):
        # EXACTLY ops/quant.py's quantize_int8 / quantize_int4 —
        # last-axis reductions are order-independent, so the staged
        # values are bit-identical to what paged_store would land.
        amax = jnp.max(jnp.abs(x), axis=-1)
        if int4:
            scale = jnp.maximum(amax / 7.0, 1e-8)
            q = jnp.clip(jnp.round(x / scale[..., None]), -7.0, 7.0)
        else:
            scale = jnp.maximum(amax / 127.0, 1e-8)
            q = jnp.round(x / scale[..., None])
        return q, scale

    kq, ks = quant(kseg)
    vq, vs = quant(vseg)
    ko_ref[0, 0] = jnp.where(inside, kq, kp)
    vo_ref[0, 0] = jnp.where(inside, vq, vp)
    rows2 = jax.lax.broadcasted_iota(jnp.int32, ks.shape, 0)
    inside2 = ((o + rows2) >= 0) & ((o + rows2) < t)  # [bs, kvh]
    kso_ref[0, 0] = jnp.where(inside2, ks, ksp_ref[0].astype(jnp.float32))
    vso_ref[0, 0] = jnp.where(inside2, vs, vsp_ref[0].astype(jnp.float32))


# oimlint: hotpath
def paged_flash_prefill(
    q, k_new, v_new, k_pool, v_pool, k_scale, v_scale, tables, starts,
    *, window: int = 0,
):
    """One prompt segment's causal attention straight off (and INTO)
    the paged pool: stage ``k_new``/``v_new`` [B, t, KVH, hd] into the
    write window ``[starts[b], starts[b] + t)`` of each row's blocks
    with fused quant (``_prefill_stage_kernel``), land the merged
    blocks through the sentinel-dropping block scatter
    (``paged_store_blocks``), then attend with the flash-decode kernel
    — whose q-row arithmetic already covers arbitrary ``t`` — over the
    updated pool.  Returns ``(out [B, t, H, hd] float32, k_pool,
    v_pool, k_scale, v_scale)``: the gather path's pre-``wo``
    attention output plus the updated pool planes, so the caller swaps
    this in exactly where it called ``paged_store`` + dense attention.

    Exactness contract: the landed bytes equal ``paged_store``'s for
    every in-window row (same quant formulas, same OOB-drop), prior
    rows and future garbage keep their current pool bytes, and the
    attend is the kernel the decode matrix already pins token-identical
    to the gather — so kernel prefill == gather prefill, token for
    token (tests/test_serve_prefill_kernel.py).

    Same one-compile property as decode: tables/starts are data, the
    segment length ``t`` is the only shape the engine varies (its
    prefill_chunk bucket — one compile per bucket, pinned by the
    jit-guard suite).
    """
    b, t, h, hd = q.shape
    n_blocks, block_size, kvh, _ = k_pool.shape
    n_tables = tables.shape[1]
    if h % kvh:
        raise ValueError(f"n_heads {h} not divisible by kv_heads {kvh}")
    if not supported_block_size(block_size, hd):
        raise ValueError(
            f"paged_flash_prefill needs block_size and head_dim each "
            f"<= {_LANES} or a multiple of {_LANES} (the lane-tiling "
            f"constraint); got block_size={block_size}, head_dim={hd} "
            f"— use the gather path (prefill_kernel=False) for this "
            f"geometry"
        )
    quantized = k_scale is not None
    int4 = bool(k_pool.dtype == jnp.int4)
    # A t-row window starting at an arbitrary in-block offset straddles
    # at most cdiv(t, bs) + 1 consecutive table entries.
    n_w = -(-t // block_size) + 1
    tables = tables.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    # Pad the new K/V by one block on each side so the staging kernel's
    # dynamic straddle slice is always in range (pad rows mask off).
    pad = ((0, 0), (block_size, block_size), (0, 0), (0, 0))
    kn = jnp.pad(k_new, pad)
    vn = jnp.pad(v_new, pad)

    def seg_map(b_, jw_, tables_ref, starts_ref):
        return (b_, 0, 0, 0)

    def pool_map(b_, jw_, tables_ref, starts_ref):
        entry = jnp.minimum(
            starts_ref[b_] // block_size + jw_, n_tables - 1
        )
        return (jnp.minimum(tables_ref[b_, entry], n_blocks - 1), 0, 0, 0)

    def pool_scale_map(b_, jw_, tables_ref, starts_ref):
        entry = jnp.minimum(
            starts_ref[b_] // block_size + jw_, n_tables - 1
        )
        return (jnp.minimum(tables_ref[b_, entry], n_blocks - 1), 0, 0)

    def out_map(b_, jw_, tables_ref, starts_ref):
        return (b_, jw_, 0, 0, 0)

    def out_scale_map(b_, jw_, tables_ref, starts_ref):
        return (b_, jw_, 0, 0)

    in_specs = [
        pl.BlockSpec((1, t + 2 * block_size, kvh, hd), seg_map),
        pl.BlockSpec((1, t + 2 * block_size, kvh, hd), seg_map),
        pl.BlockSpec((1, block_size, kvh, hd), pool_map),
        pl.BlockSpec((1, block_size, kvh, hd), pool_map),
    ]
    operands = [kn, vn, k_pool, v_pool]
    out_specs = [
        pl.BlockSpec((1, 1, block_size, kvh, hd), out_map),
        pl.BlockSpec((1, 1, block_size, kvh, hd), out_map),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b, n_w, block_size, kvh, hd), jnp.float32),
        jax.ShapeDtypeStruct((b, n_w, block_size, kvh, hd), jnp.float32),
    ]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_size, kvh), pool_scale_map),
            pl.BlockSpec((1, block_size, kvh), pool_scale_map),
        ]
        operands += [k_scale, v_scale]
        out_specs += [
            pl.BlockSpec((1, 1, block_size, kvh), out_scale_map),
            pl.BlockSpec((1, 1, block_size, kvh), out_scale_map),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((b, n_w, block_size, kvh), jnp.float32),
            jax.ShapeDtypeStruct((b, n_w, block_size, kvh), jnp.float32),
        ]
    staged = pl.pallas_call(
        functools.partial(
            _prefill_stage_kernel,
            t=t, block_size=block_size, quantized=quantized, int4=int4,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, n_w),
            in_specs=in_specs,
            out_specs=out_specs,
        ),
        out_shape=out_shape,
        interpret=_interpret(),
    )(tables, starts, *operands)
    if quantized:
        ko, vo, kso, vso = staged
    else:
        (ko, vo), kso, vso = staged, None, None
    # Landing ids: the window's table entries, sentinel for anything
    # past the table or already-sentinel (drops at the pool edge).
    entries = starts[:, None] // block_size + jnp.arange(n_w)[None, :]
    ids = jnp.take_along_axis(
        tables, jnp.minimum(entries, n_tables - 1), axis=1
    )
    ids = jnp.where(
        (entries < n_tables) & (ids < n_blocks), ids, n_blocks
    ).reshape(-1)
    bw = b * n_w
    k_pool, k_scale = paged_store_blocks(
        k_pool, k_scale, ko.reshape(bw, block_size, kvh, hd),
        None if kso is None else kso.reshape(bw, block_size, kvh), ids,
    )
    v_pool, v_scale = paged_store_blocks(
        v_pool, v_scale, vo.reshape(bw, block_size, kvh, hd),
        None if vso is None else vso.reshape(bw, block_size, kvh), ids,
    )
    out = paged_flash_decode(
        q, k_pool, v_pool, k_scale, v_scale, tables, starts,
        window=window,
    )
    return out, k_pool, v_pool, k_scale, v_scale
