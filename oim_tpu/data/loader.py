"""Deterministic sharded token-batch loading.

The host-side feed for the training path (new work for the TPU build —
the reference is a storage control plane with no input pipeline;
SURVEY.md §2.3).  Design points, all TPU-driven:

- **Process-sharded, deterministic.**  Every host computes the same
  global shuffle from the same seed and takes its own disjoint slice
  by ``(process_index, num_processes)`` — no coordination traffic on the
  control plane, which stays "short-lived, infrequent connections"
  (reference README.md:47-49).  Epoch reshuffles derive from
  ``fold_in(seed, epoch)`` so any step is reproducible from (seed, step)
  alone — that is what makes checkpoint/resume exact.
- **Static shapes.**  Every batch is exactly ``[batch_local, seq+1]``
  (inputs and shifted targets share the +1); ragged tails are dropped,
  never padded — a padded tail would recompile the train step.
- **Memmap-friendly.**  Sources are numpy arrays / memmaps and the
  *source reads* are plain slices — but gathering a shuffled batch
  necessarily copies each window into a freshly allocated batch array
  (budget ~``batch·(seq+1)·itemsize`` per step, not corpus-sized).  The
  device transfer happens in the prefetcher (oim_tpu.data.prefetch),
  not here.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Iterator

import numpy as np

from oim_tpu.common import metrics

# Data-plane instruments: the training input pipeline touched metrics
# nowhere, so a host-bound feed was invisible until step time regressed.
# Assembly is sub-millisecond when healthy — FAST_BUCKETS, not the 1ms-
# floor control-plane buckets.
_BATCHES = metrics.registry().counter(
    "oim_data_batches_total",
    "Token batches assembled by the input pipeline.",
)
_ASSEMBLY = metrics.registry().histogram(
    "oim_data_batch_assembly_seconds",
    "Host-side batch gather latency (shuffled windows to one batch "
    "array), per batch.",
    buckets=metrics.FAST_BUCKETS,
)


@dataclass(frozen=True)
class ShardSpec:
    """Which slice of the global batch this process feeds."""

    process_index: int = 0
    num_processes: int = 1

    def __post_init__(self):
        if not 0 <= self.process_index < self.num_processes:
            raise ValueError(
                f"process_index {self.process_index} out of range for "
                f"{self.num_processes} processes"
            )


def window_count(n_tokens: int, seq: int) -> int:
    """Number of non-overlapping [seq+1]-token windows in a corpus."""
    return max((n_tokens - 1) // seq, 0)


class TokenBatches:
    """Iterates deterministic ``[batch_local, seq+1]`` int32 batches over a
    flat token corpus, sharded across processes.

    The corpus is cut into non-overlapping windows of ``seq+1`` tokens
    (window i covers ``[i*seq, i*seq + seq + 1)`` — adjacent windows share
    one boundary token so every target is some window's input).  Windows
    are shuffled per epoch, then dealt round-robin to the global batch;
    this process materializes only rows ``process_index::num_processes``
    of each global batch.
    """

    def __init__(
        self,
        tokens: np.ndarray,
        batch_global: int,
        seq: int,
        shard: ShardSpec = ShardSpec(),
        seed: int = 0,
        epochs: int | None = None,
    ) -> None:
        tokens = np.asarray(tokens)
        if tokens.ndim != 1:
            raise ValueError(f"corpus must be 1-D, got shape {tokens.shape}")
        if batch_global % shard.num_processes:
            raise ValueError(
                f"global batch {batch_global} not divisible by "
                f"{shard.num_processes} processes"
            )
        self.tokens = tokens
        self.batch_global = batch_global
        self.batch_local = batch_global // shard.num_processes
        self.seq = seq
        self.shard = shard
        self.seed = seed
        self.epochs = epochs
        self.n_windows = window_count(len(tokens), seq)
        if self.n_windows < batch_global:
            raise ValueError(
                f"corpus has {self.n_windows} windows of seq={seq}, "
                f"need at least batch_global={batch_global}"
            )
        self.steps_per_epoch = self.n_windows // batch_global
        self._order_cache: tuple[int, np.ndarray] | None = None

    def _epoch_order(self, epoch: int) -> np.ndarray:
        # One-slot memo: sequential iteration calls batch_at once per step
        # and an O(n_windows) reshuffle per *step* (vs per epoch) would
        # compete with the batch assembly the prefetcher overlaps.
        cached = self._order_cache
        if cached is not None and cached[0] == epoch:
            return cached[1]
        rng = np.random.default_rng((self.seed, epoch))
        order = rng.permutation(self.n_windows)
        self._order_cache = (epoch, order)
        return order

    def batch_at(self, step: int) -> np.ndarray:
        """The local batch for a global step (any step, random access —
        this is the resume path: no iterator state to restore)."""
        t0 = time.perf_counter()
        epoch, within = divmod(step, self.steps_per_epoch)
        order = self._epoch_order(epoch)
        start = within * self.batch_global
        rows = order[
            start
            + self.shard.process_index : start
            + self.batch_global : self.shard.num_processes
        ]
        out = np.empty((self.batch_local, self.seq + 1), np.int32)
        for i, w in enumerate(rows):
            out[i] = self.tokens[w * self.seq : w * self.seq + self.seq + 1]
        _ASSEMBLY.observe(time.perf_counter() - t0)
        _BATCHES.inc()
        return out

    def __iter__(self) -> Iterator[np.ndarray]:
        step = 0
        while True:
            if (
                self.epochs is not None
                and step >= self.epochs * self.steps_per_epoch
            ):
                return
            yield self.batch_at(step)
            step += 1


def split_batch(batch: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """``[B, seq+1]`` → (inputs ``[B, seq]``, targets ``[B, seq]``)."""
    return batch[:, :-1], batch[:, 1:]


def pack_documents(
    documents: "list[list[int]] | list[np.ndarray]",
    seq: int,
    sep_id: int,
) -> np.ndarray:
    """Greedy sequence packing: documents → ``[n_rows, seq]`` token rows.

    Each document is prefixed with ``sep_id`` (BOS-style — the separator
    opens the document it precedes, matching ``TransformerConfig.
    doc_sep_id`` semantics) and rows are filled greedily in order; a
    document that does not fit the remaining row starts a new one, and
    documents longer than ``seq - 1`` are split into maximal chunks,
    each re-prefixed with the separator (the continuation loses its
    earlier context — the standard packing trade-off, traded against
    zero padding waste).  Row tails pad with runs of ``sep_id``: every
    extra separator opens an empty document, so padded positions attend
    only to themselves and contribute nothing to the loss (separator
    labels are masked — models/train.py ``_shifted_labels``).
    """
    if seq < 2:
        raise ValueError(f"seq={seq} leaves no room for sep + token")
    rows: list[list[int]] = []
    current: list[int] = []
    for doc in documents:
        doc = [int(t) for t in doc]
        if any(t == sep_id for t in doc):
            raise ValueError(
                f"document contains the separator id {sep_id}"
            )
        if not doc:
            continue
        for start in range(0, len(doc), seq - 1):
            chunk = doc[start : start + seq - 1]
            if len(current) + 1 + len(chunk) > seq:
                rows.append(current)
                current = []
            current += [sep_id] + chunk
    if current:
        rows.append(current)
    if not rows:
        return np.empty((0, seq), np.int32)
    out = np.full((len(rows), seq), sep_id, np.int32)
    for i, row in enumerate(rows):
        out[i, : len(row)] = row
    return out
