"""Input pipeline: deterministic sharded token batches + device prefetch.

``TokenBatches`` deals non-overlapping corpus windows into per-process
batch rows (resumable by step, no iterator state); ``device_prefetch``
keeps N batches committed to devices ahead of the train loop.
"""

from oim_tpu.data.loader import (
    ShardSpec,
    TokenBatches,
    pack_documents,
    split_batch,
    window_count,
)
from oim_tpu.data.prefetch import device_prefetch, to_global

__all__ = [
    "ShardSpec",
    "TokenBatches",
    "pack_documents",
    "split_batch",
    "window_count",
    "device_prefetch",
    "to_global",
]
