"""Background host→device prefetch.

Overlaps the host-side batch assembly + PCIe/HBM transfer with device
compute: a daemon thread stays ``buffer_size`` batches ahead, each already
committed to devices as a global ``jax.Array`` with the caller's
``NamedSharding`` — by the time the train step wants batch N+1, its
transfer started while step N was running.  This is the host-feed analog
of the reference keeping its data plane out of the control path
(reference README.md:47-49): the train loop never blocks on IO unless the
host genuinely cannot keep up.

Multi-host: each process feeds only its local rows;
``jax.make_array_from_process_local_data`` assembles the logical global
array across processes (single-process it degenerates to a device_put).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterable, Iterator

import jax
import numpy as np

from oim_tpu.common import metrics

# Prefetch observability: depth says whether the buffer is doing its job
# (pinned at 0 = the host cannot keep up; pinned at buffer_size = device-
# bound, all good), wait time says what that costs the train step.
_DEPTH = metrics.registry().gauge(
    "oim_data_prefetch_depth",
    "Batches ready in the host-to-device prefetch buffer at the last "
    "consumer wakeup.",
)
_WAIT = metrics.registry().histogram(
    "oim_data_batch_wait_seconds",
    "Time the consumer blocked waiting for the next prefetched batch "
    "(sustained milliseconds here = input-pipeline-bound training).",
    buckets=metrics.FAST_BUCKETS,
)


class _Stop:
    pass


_STOP = _Stop()


def to_global(batch: np.ndarray, sharding: jax.sharding.NamedSharding):
    """Commit one process-local batch to devices as a global array."""
    return jax.make_array_from_process_local_data(sharding, batch)


def device_prefetch(
    batches: Iterable[np.ndarray],
    sharding: jax.sharding.NamedSharding,
    buffer_size: int = 2,
) -> Iterator[jax.Array]:
    """Yields device-resident global arrays, ``buffer_size`` ahead.

    The producer thread is a daemon and dies with the process; on normal
    exhaustion (or an exception in the source iterator) the consumer sees
    the end/exception at the point it would have consumed that batch.
    Closing the generator (``.close()`` / GC / ``break``) unblocks and
    stops the producer.
    """
    if buffer_size < 1:
        raise ValueError(f"buffer_size must be >= 1, got {buffer_size}")
    buf: queue.Queue = queue.Queue(maxsize=buffer_size)
    stop = threading.Event()

    def put_or_stop(item) -> None:
        while not stop.is_set():
            try:
                buf.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def produce():
        try:
            it = iter(batches)
            while not stop.is_set():
                try:
                    batch = next(it)
                except StopIteration:
                    put_or_stop(_STOP)
                    return
                put_or_stop(to_global(np.asarray(batch), sharding))
        except BaseException as exc:  # surface in the consumer
            put_or_stop(exc)

    thread = threading.Thread(target=produce, daemon=True, name="oim-prefetch")

    def consume():
        # Start producing only once actually iterated: a generator that is
        # never advanced never runs its body (or its finally), so an eager
        # start would leak the thread + buffered device arrays.
        thread.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = buf.get()
                _WAIT.observe(time.perf_counter() - t0)
                _DEPTH.set(buf.qsize())
                if isinstance(item, _Stop):
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    return consume()
