"""Per-device controller (≙ reference pkg/oim-controller)."""

from oim_tpu.controller.controller import Controller
from oim_tpu.controller.keymutex import KeyMutex

__all__ = ["Controller", "KeyMutex"]
