"""Per-key mutual exclusion.

≙ the reference's keymutex serializing all operations on one volume
(reference pkg/oim-controller/controller.go:44-51,
pkg/oim-csi-driver/serialize.go:13-16): concurrent RPCs for different
volumes proceed in parallel; same-volume RPCs are strictly ordered.
Locks are refcounted so idle keys do not accumulate.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator


class KeyMutex:
    def __init__(self) -> None:
        self._guard = threading.Lock()
        self._locks: dict[str, tuple[threading.Lock, int]] = {}

    @contextlib.contextmanager
    def locked(self, key: str) -> Iterator[None]:
        with self._guard:
            lock, refs = self._locks.get(key, (threading.Lock(), 0))
            self._locks[key] = (lock, refs + 1)
        lock.acquire()
        try:
            yield
        finally:
            lock.release()
            with self._guard:
                lock2, refs = self._locks[key]
                if refs == 1:
                    del self._locks[key]
                else:
                    self._locks[key] = (lock2, refs - 1)
