"""The per-device controller: volumes ↔ TPU sub-slices.

≙ reference pkg/oim-controller/controller.go:

- ``MapVolume`` ensures the allocation exists (pre-provisioned allocations
  must already exist, like Malloc BDevs; on-demand ones are created, like
  Ceph BDevs; controller.go:55-156) and attaches it idempotently, returning
  chip device paths + PCI addresses + ICI mesh coordinates and the JAX
  distributed-coordinator rendezvous (the generalization of PCI BDF +
  SCSI target/LUN).
- ``UnmapVolume`` detaches and deletes *on-demand* allocations, keeping
  pre-provisioned ones (controller.go:159-212); unknown volumes succeed.
- ``ProvisionSlice``/``CheckSlice`` manage pre-provisioned allocations
  (≙ ProvisionMallocBDev/CheckMallocBDev, controller.go:215-278).
- Per-volume serialization via KeyMutex (controller.go:44-51).
- Background self-registration heartbeat re-``SetValue``-ing the
  controller's address so the registry survives DB loss
  (controller.go:411-468).
"""

from __future__ import annotations

import threading
import time

import grpc

from oim_tpu import log
from oim_tpu.agent import Agent, AgentError
from oim_tpu.agent import EBUSY, EEXIST, ENODEV, ENOSPC
from oim_tpu.common import pci as pcilib
from oim_tpu.common import events, metrics, resilience, tracing
from oim_tpu.common.interceptors import LogServerInterceptor, PeerCheckInterceptor
from oim_tpu.common.server import NonBlockingGRPCServer
from oim_tpu.common.tlsconfig import TLSConfig
from oim_tpu.controller.keymutex import KeyMutex
from oim_tpu.spec import CONTROLLER, REGISTRY, oim_pb2

from oim_tpu.common.regdial import REGISTRY_CN  # one definition
DEFAULT_REGISTRY_DELAY = 60.0


def _agent_error_to_status(exc: AgentError) -> grpc.StatusCode:
    return {
        ENOSPC: grpc.StatusCode.RESOURCE_EXHAUSTED,
        ENODEV: grpc.StatusCode.NOT_FOUND,
        EEXIST: grpc.StatusCode.ALREADY_EXISTS,
        EBUSY: grpc.StatusCode.FAILED_PRECONDITION,
    }.get(exc.code, grpc.StatusCode.INTERNAL)


class Controller:
    """gRPC servicer for oim.v1.Controller backed by one tpu-agent."""

    def __init__(
        self,
        controller_id: str,
        agent_socket: str,
        registry_address: str = "",
        tls: TLSConfig | None = None,
        registry_delay: float = DEFAULT_REGISTRY_DELAY,
        coordinator_host: str = "127.0.0.1",
        health_interval: float = 0.0,
    ) -> None:
        self.controller_id = controller_id
        self.agent_socket = agent_socket
        self.registry_address = registry_address
        self.tls = tls
        self.registry_delay = registry_delay
        self.coordinator_host = coordinator_host
        # > 0 starts a HealthReporter next to the address heartbeat
        # (oim_tpu/health): leased health/<id>/<chip> keys each interval.
        self.health_interval = health_interval
        self._mutex = KeyMutex()
        # MapVolume idempotency cache, volume_id-keyed: the last successful
        # reply (+ whether the allocation was pre-provisioned).  A retried
        # MapVolume that lands AFTER its first attempt succeeded — the
        # ambiguous "request executed, reply lost" window the shared retry
        # layer creates on purpose — returns the original placement from
        # here instead of re-driving the agent (or ENOSPC-ing a second
        # allocation).  Entries die on UnmapVolume / ProvisionSlice-delete,
        # so the cache can never outlive the mapping it describes.
        self._idem_replies: dict[str, tuple[oim_pb2.MapVolumeReply, bool]] = {}
        # Registry-hop retry policy: bounded well below the heartbeat
        # period so one slow ladder can never pile onto the next beat.
        self._registry_retry = resilience.RetryPolicy.for_heartbeat(
            registry_delay
        )
        self._agent_cache = resilience.ConnCache(
            lambda: Agent(self.agent_socket)
        )
        # Heartbeat state (Start/Close).
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._health_reporter = None
        self._event_publisher = None
        self._closed = False
        self._advertised_address = ""
        # Chip occupancy, evaluated against the agent at scrape time (so
        # the gauge can never drift from the allocator's truth).  Scrapes
        # use their own short-timeout connection: a hung agent must stall
        # the scrape for 2s, not block live MapVolume RPCs on the shared
        # client's lock; a dead one is dropped so the next scrape
        # re-dials instead of failing forever.
        self._scrape_conn = resilience.ConnCache(
            lambda: Agent(self.agent_socket, timeout=2.0)
        )
        # Gauge values are cached with a staleness bound so a wedged agent
        # adds at most ONE 2s stall per TTL to /metrics renders (not 2s per
        # series per scrape), and a scrape failure serves the last good
        # value while oim_metrics_scrape_errors_total records that the
        # series is stale instead of letting it silently vanish.
        self._scrape_cache: dict[str, tuple[float, float]] = {}
        self._scrape_cache_lock = threading.Lock()
        self._scrape_errors = metrics.registry().counter(
            "oim_metrics_scrape_errors_total",
            "Agent scrape failures during /metrics renders (served stale).",
            ("controller",),
        )
        self._chips_gauge = metrics.registry().gauge(
            "oim_chips_total", "Chips the device-plane agent owns.",
            ("controller",),
        )
        self._chips_cb = lambda: self._cached_scrape(
            "chips", lambda: len(self._scrape(lambda a: a.get_chips()))
        )
        self._chips_gauge.set_function(self._chips_cb, controller_id)
        self._allocated_gauge = metrics.registry().gauge(
            "oim_chips_allocated", "Chips attached to mapped volumes.",
            ("controller",),
        )
        self._allocated_cb = lambda: self._cached_scrape(
            "allocated",
            lambda: sum(
                len(a.get("chips", ()))
                for a in self._scrape(lambda ag: ag.get_allocations())
                if a.get("attached")
            ),
        )
        self._allocated_gauge.set_function(self._allocated_cb, controller_id)

    # -- agent connection --------------------------------------------------

    def agent(self) -> Agent:
        """Lazy, auto-reconnecting agent connection (the reference connects
        to SPDK at New() time, controller.go:379-408; lazy lets the daemon
        and controller start in any order).  The dial-outside-the-lock /
        close-latch discipline lives in resilience.ConnCache: a wedged
        daemon costs the dialing thread its socket timeout, never
        close() or other RPC threads."""
        return self._agent_cache.get()

    SCRAPE_CACHE_TTL = 10.0

    def _cached_scrape(self, name: str, fn):
        """``fn()`` with a TTL cache; on failure serve the last good value
        (bumping the scrape-error counter) rather than vanishing the
        series.  One lock over check→scrape→stamp so concurrent renders
        (ThreadingHTTPServer) cannot each pay the scrape stall."""
        with self._scrape_cache_lock:
            now = time.monotonic()
            cached = self._scrape_cache.get(name)
            if cached is not None and now - cached[1] < self.SCRAPE_CACHE_TTL:
                if cached[0] is None:  # recent failure, no good value yet
                    raise RuntimeError(
                        f"agent scrape {name!r} failing (cooling down)"
                    )
                return cached[0]
            try:
                value = float(fn())
            except Exception:
                self._scrape_errors.inc(self.controller_id)
                # Re-stamp stale value OR a failure sentinel: a wedged
                # agent costs one timeout per series per TTL even before
                # the first successful scrape, not one per render.
                stale = cached[0] if cached is not None else None
                self._scrape_cache[name] = (stale, now)
                if stale is not None:
                    return stale
                raise
            self._scrape_cache[name] = (value, now)
            return value

    def _scrape(self, fn):
        """Run ``fn(agent)`` on the metrics-only connection, dropping it on
        any failure so the next scrape starts from a fresh dial (same
        ConnCache discipline as ``agent()``: a wedged daemon costs this
        scrape its 2s timeout, never close() or other renders)."""
        try:
            return fn(self._scrape_conn.get())
        except BaseException:
            self._scrape_conn.drop()
            raise

    def _drop_agent(self) -> None:
        self._agent_cache.drop()

    def _call_agent(self, context, fn, *args, **kwargs):
        """Invoke an agent method, mapping transport failures to UNAVAILABLE
        and protocol errors to their gRPC status."""
        try:
            return fn(self.agent(), *args, **kwargs)
        except AgentError:
            raise
        except (ConnectionError, OSError) as exc:
            self._drop_agent()
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"tpu-agent at {self.agent_socket} unavailable: {exc}",
            )

    # -- Controller service ------------------------------------------------

    def MapVolume(self, request: oim_pb2.MapVolumeRequest, context) -> oim_pb2.MapVolumeReply:
        volume_id = request.volume_id
        if not volume_id:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "volume_id required")
        which = request.WhichOneof("params")
        t0 = time.perf_counter()
        with self._mutex.locked(volume_id):
            cached = self._idem_replies.get(volume_id)
            if cached is not None and self._idem_compatible(request, *cached):
                def cache_hit() -> oim_pb2.MapVolumeReply:
                    # Emitted only on the paths that actually ANSWER from
                    # the cache — a stale entry that falls through to the
                    # agent must not leave a misleading cache-hit row.
                    events.emit(
                        "volume.map.cache-hit",
                        component="oim-controller",
                        subject=volume_id,
                        controller=self.controller_id,
                    )
                    return cached[0]
                # Retry after a lost reply: hand back the original
                # placement — but only after checking it against the
                # device plane, because a restarted agent comes back
                # EMPTY (volatile allocations) and the cache must never
                # outlive the allocation it describes.  An *unreachable*
                # agent is the one case the cache answers alone: that is
                # exactly the mid-recovery window a duplicate of an
                # already-acknowledged request arrives in.
                try:
                    alloc = self.agent().find_allocation(volume_id)
                except (ConnectionError, OSError):
                    self._drop_agent()
                    return cache_hit()
                except AgentError:
                    # The agent is up but answered with an application
                    # error: fall through and let the normal path map it
                    # to a precise status (_call_agent), not UNKNOWN.
                    pass
                else:
                    if alloc is not None:
                        return cache_hit()
                    self._idem_replies.pop(volume_id, None)  # wiped
            alloc = self._call_agent(
                context, lambda a: a.find_allocation(volume_id)
            )
            if alloc is None:
                if which == "slice":
                    topology = list(request.slice.topology.dims) or None
                    try:
                        alloc = self._call_agent(
                            context,
                            lambda a: a.create_allocation(
                                volume_id,
                                request.slice.chip_count,
                                topology=topology,
                            ),
                        )
                    except AgentError as exc:
                        events.emit(
                            "volume.map.alloc-failed",
                            component="oim-controller",
                            severity=events.ERROR,
                            subject=volume_id,
                            controller=self.controller_id,
                            code=_agent_error_to_status(exc).name,
                            error=str(exc),
                        )
                        context.abort(_agent_error_to_status(exc), str(exc))
                elif which == "provisioned":
                    # Pre-provisioned allocations must already exist
                    # (≙ Malloc BDevs, controller.go:75-95).
                    context.abort(
                        grpc.StatusCode.NOT_FOUND,
                        f"no provisioned allocation {volume_id!r}",
                    )
                else:
                    context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        "MapVolumeRequest.params required for a new volume",
                    )
            elif which == "provisioned" and not alloc["provisioned"]:
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"allocation {volume_id!r} exists but is on-demand, "
                    "not provisioned",
                )
            elif which == "slice":
                # Idempotency check: an existing mapping must be compatible
                # (≙ the reference rejecting size mismatches on re-map).
                if request.slice.chip_count and (
                    alloc["chip_count"] != request.slice.chip_count
                ):
                    context.abort(
                        grpc.StatusCode.ALREADY_EXISTS,
                        f"volume {volume_id!r} already mapped with "
                        f"{alloc['chip_count']} chips",
                    )
                requested_topology = list(request.slice.topology.dims)
                if requested_topology and alloc["mesh"] != requested_topology:
                    context.abort(
                        grpc.StatusCode.ALREADY_EXISTS,
                        f"volume {volume_id!r} already mapped with mesh "
                        f"{alloc['mesh']}, not {requested_topology}",
                    )
            try:
                attached = self._call_agent(
                    context, lambda a: a.attach_allocation(volume_id)
                )
            except AgentError as exc:
                context.abort(_agent_error_to_status(exc), str(exc))
            reply = self._reply_from_allocation(attached)
            self._idem_replies[volume_id] = (reply, attached["provisioned"])
        events.emit(
            "volume.map",
            component="oim-controller",
            subject=volume_id,
            controller=self.controller_id,
            chips=len(reply.chips),
            duration_ms=round((time.perf_counter() - t0) * 1e3, 3),
        )
        return reply

    @staticmethod
    def _idem_compatible(
        request: oim_pb2.MapVolumeRequest,
        reply: oim_pb2.MapVolumeReply,
        provisioned: bool,
    ) -> bool:
        """Is ``request`` a re-send of the mapping ``reply`` answered?
        Incompatible requests fall through to the agent-backed path,
        which produces the precise error (ALREADY_EXISTS / NOT_FOUND)."""
        which = request.WhichOneof("params")
        if which == "provisioned":
            return provisioned
        if which == "slice":
            if request.slice.chip_count and (
                request.slice.chip_count != len(reply.chips)
            ):
                return False
            requested_topology = list(request.slice.topology.dims)
            if requested_topology and requested_topology != list(reply.mesh.dims):
                return False
            return True
        # No params: "whatever is already mapped" — any cached reply fits.
        return True

    def _reply_from_allocation(self, alloc: dict) -> oim_pb2.MapVolumeReply:
        reply = oim_pb2.MapVolumeReply(
            mesh=oim_pb2.MeshShape(dims=alloc["mesh"]),
            coordinator_address=(
                f"{self.coordinator_host}:{alloc['coordinator_port']}"
                if alloc.get("coordinator_port")
                else ""
            ),
            num_processes=1,
            process_id=0,
        )
        for chip in alloc["chips"]:
            assignment = reply.chips.add(
                chip_id=chip["chip_id"],
                device_path=chip["device_path"],
                coord=oim_pb2.MeshCoord(coords=chip["coord"]),
            )
            try:
                parsed = pcilib.parse_bdf_string(chip["pci"])
                assignment.pci.domain = parsed.domain
                assignment.pci.bus = parsed.bus
                assignment.pci.device = parsed.device
                assignment.pci.function = parsed.function
            except ValueError:
                # Unknown address: leave all components at the UNKNOWN
                # encoding for registry-default completion.
                assignment.pci.domain = pcilib.UNKNOWN
                assignment.pci.bus = pcilib.UNKNOWN
                assignment.pci.device = pcilib.UNKNOWN
                assignment.pci.function = pcilib.UNKNOWN
        return reply

    def UnmapVolume(self, request: oim_pb2.UnmapVolumeRequest, context) -> oim_pb2.UnmapVolumeReply:
        volume_id = request.volume_id
        if not volume_id:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "volume_id required")
        with self._mutex.locked(volume_id):
            # Invalidate BEFORE driving the agent: even a half-failed
            # unmap means the old placement may no longer be truthful, so
            # a later Map retry must re-derive it from the device plane.
            self._idem_replies.pop(volume_id, None)
            alloc = self._call_agent(
                context, lambda a: a.find_allocation(volume_id)
            )
            if alloc is None:
                return oim_pb2.UnmapVolumeReply()  # idempotent
            try:
                if alloc["attached"]:
                    self._call_agent(
                        context, lambda a: a.detach_allocation(volume_id)
                    )
                if not alloc["provisioned"]:
                    # On-demand allocations are torn down; pre-provisioned
                    # ones persist (≙ delete non-Malloc BDev,
                    # controller.go:190-209).
                    self._call_agent(
                        context, lambda a: a.delete_allocation(volume_id)
                    )
            except AgentError as exc:
                if exc.code != ENODEV:
                    context.abort(_agent_error_to_status(exc), str(exc))
        events.emit(
            "volume.unmap",
            component="oim-controller",
            subject=volume_id,
            controller=self.controller_id,
        )
        return oim_pb2.UnmapVolumeReply()

    def ProvisionSlice(self, request: oim_pb2.ProvisionSliceRequest, context) -> oim_pb2.ProvisionSliceReply:
        name = request.name
        if not name:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "name required")
        with self._mutex.locked(name):
            # Either branch changes (or re-derives) what the name maps
            # to, so any cached MapVolume placement for it is suspect:
            # a re-provision after an agent wipe lands on different
            # chips, and the cache must never outlive the allocation it
            # describes.
            self._idem_replies.pop(name, None)
            if request.chip_count > 0:
                try:
                    alloc = self._call_agent(
                        context,
                        lambda a: a.create_allocation(
                            name, request.chip_count, provisioned=True
                        ),
                    )
                except AgentError as exc:
                    context.abort(_agent_error_to_status(exc), str(exc))
                if not alloc["provisioned"]:
                    # Idempotent create returned an existing *on-demand*
                    # allocation — the name is taken by a different kind of
                    # resource.
                    context.abort(
                        grpc.StatusCode.ALREADY_EXISTS,
                        f"{name!r} is in use by an on-demand allocation",
                    )
            else:
                # chip_count == 0 deletes, idempotently
                # (≙ controller.go:238-252).
                try:
                    alloc = self._call_agent(
                        context, lambda a: a.find_allocation(name)
                    )
                    if alloc is not None:
                        if alloc["attached"]:
                            self._call_agent(
                                context, lambda a: a.detach_allocation(name)
                            )
                        self._call_agent(
                            context, lambda a: a.delete_allocation(name)
                        )
                except AgentError as exc:
                    if exc.code != ENODEV:
                        context.abort(_agent_error_to_status(exc), str(exc))
        events.emit(
            "slice.provision" if request.chip_count > 0 else "slice.delete",
            component="oim-controller",
            subject=name,
            controller=self.controller_id,
            chips=request.chip_count,
        )
        return oim_pb2.ProvisionSliceReply()

    def CheckSlice(self, request: oim_pb2.CheckSliceRequest, context) -> oim_pb2.CheckSliceReply:
        name = request.name
        if not name:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "name required")
        alloc = self._call_agent(context, lambda a: a.find_allocation(name))
        if alloc is None or not (
            alloc["provisioned"] or request.include_unprovisioned
        ):
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"no provisioned allocation {name!r}"
            )
        return oim_pb2.CheckSliceReply(chip_count=alloc["chip_count"])

    def GetTopology(self, request: oim_pb2.GetTopologyRequest, context) -> oim_pb2.GetTopologyReply:
        """Chip inventory for remote GetCapacity — the reference declared
        remote capacity but never plumbed it (≙ controllerserver.go:150-159)."""
        topo = self._call_agent(context, lambda a: a.get_topology())
        return oim_pb2.GetTopologyReply(
            chip_count=topo["chip_count"],
            free_chips=topo["free_chips"],
            mesh=oim_pb2.MeshShape(dims=topo["mesh"]),
            accel_type=topo.get("accel_type", ""),
        )

    def ListSlices(self, request: oim_pb2.ListSlicesRequest, context) -> oim_pb2.ListSlicesReply:
        """Allocation inventory for CSI ListVolumes
        (≙ controllerserver.go:161, get_vhost_controllers)."""
        allocs = self._call_agent(context, lambda a: a.get_allocations())
        reply = oim_pb2.ListSlicesReply()
        for alloc in allocs:
            reply.slices.add(
                name=alloc["name"],
                chip_count=alloc["chip_count"],
                mesh=oim_pb2.MeshShape(dims=alloc["mesh"]),
                provisioned=alloc["provisioned"],
                attached=alloc["attached"],
            )
        return reply

    # -- self-registration heartbeat ---------------------------------------

    def start(self, advertised_address: str) -> None:
        """Begin re-registering ``<id>/address`` every ``registry_delay``
        seconds (immediately, then periodically; ≙ controller.go:411-443).
        No-op when no registry is configured (local mode)."""
        if not self.registry_address:
            return
        self._advertised_address = advertised_address
        self._stop.clear()
        self._closed = False
        self._thread = threading.Thread(
            target=self._register_loop, daemon=True, name="controller-register"
        )
        self._thread.start()
        # Durable flight-recorder publication: WARNING+ events mirror to
        # leased events/controller.<id>/<seq> keys (the source doubles as
        # the TLS CN, matching the registry's events/ authz subtree).
        self._event_publisher = events.RegistryEventPublisher(
            f"controller.{self.controller_id}",
            self.registry_address,
            tls=self.tls,
        ).start()
        if self.health_interval > 0:
            # Chip-health telemetry rides the same lease discipline as the
            # address heartbeat (oim_tpu/health/reporter.py).
            from oim_tpu.health import HealthReporter

            self._health_reporter = HealthReporter(
                self.controller_id,
                self.agent_socket,
                self.registry_address,
                tls=self.tls,
                interval=self.health_interval,
            ).start()

    def _register_loop(self) -> None:
        while True:
            try:
                self.register()
            except grpc.RpcError as exc:
                if self._stop.is_set():
                    return  # shutting down: the failure is expected noise
                log.current().warning(
                    "registration failed",
                    registry=self.registry_address,
                    # None-code-safe: a locally raised RpcError must cost
                    # one beat, not kill the heartbeat thread.
                    error=resilience.error_text(exc),
                )
            except Exception as exc:
                # Never let the heartbeat thread die: a transient local
                # failure (cert rotation mid-read, bad address) must not
                # permanently de-register the controller.
                log.current().error(
                    "registration error",
                    registry=self.registry_address,
                    error=str(exc),
                )
            if self._stop.wait(self.registry_delay):
                return

    def register(self) -> None:
        """One registration: fresh dial → SetValue → close (per-operation
        connections survive registry restarts, ≙ controller.go:448-468).
        Bounded retries under the shared policy: a registry hiccup inside
        one beat heals within the beat instead of waiting a whole
        ``registry_delay`` for the next one — which matters because the
        address lease is only 3 beats deep."""
        from oim_tpu.common.regdial import registry_channel

        def beat(attempt):
            # Per-attempt timeout shrinks to the ladder's remaining
            # budget: a hanging registry cannot stall a beat past the
            # deadline the policy promises.
            timeout = attempt.clamped()
            with registry_channel(self.registry_address, self.tls) as channel:
                REGISTRY.stub(channel).SetValue(
                    oim_pb2.SetValueRequest(
                        value=oim_pb2.Value(
                            path=f"{self.controller_id}/address",
                            value=self._advertised_address,
                        ),
                        # Lease-scoped liveness: a crashed controller's
                        # address expires a few missed heartbeats after
                        # the last refresh instead of surviving until
                        # overwritten.
                        ttl_seconds=max(1, int(self.registry_delay * 3)),
                    ),
                    timeout=timeout,
                )

        resilience.call_with_retry(
            beat,
            self._registry_retry,
            component="oim-controller",
            op="Register",
        )
        log.current().debug(
            "registered", id=self.controller_id, address=self._advertised_address
        )

    def close(self) -> None:
        """Stop the heartbeat, the health reporter, and agent connections.
        Idempotent: `close(); close()` neither raises nor leaks threads —
        every shutdown step either guards on state it nulls out or is a
        no-op the second time."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._health_reporter is not None:
            self._health_reporter.close()
            self._health_reporter = None
        if self._event_publisher is not None:
            self._event_publisher.close()
            self._event_publisher = None
        if self._closed:
            return
        self._closed = True
        # Latched: a dial that was in flight when close() ran is closed
        # on arrival instead of installed (resilience.ConnCache), so
        # shutdown cannot leak a late connection.
        self._agent_cache.close()
        self._scrape_conn.close()
        # Deregister the gauge series — but only if a newer controller
        # with the same id hasn't already taken them over.
        self._chips_gauge.remove(self.controller_id, fn=self._chips_cb)
        self._allocated_gauge.remove(
            self.controller_id, fn=self._allocated_cb
        )

    # -- serving -----------------------------------------------------------

    def start_server(
        self, endpoint: str, require_registry_peer: bool = True
    ) -> NonBlockingGRPCServer:
        """Serve the Controller service.  With TLS, only the registry's CN is
        accepted as a client (≙ the reference controller expecting
        component.registry)."""
        interceptors: tuple = (
            tracing.TraceServerInterceptor("oim-controller"),
            metrics.MetricsServerInterceptor("oim-controller"),
            LogServerInterceptor(),
        )
        if self.tls is not None and require_registry_peer:
            interceptors = (PeerCheckInterceptor(REGISTRY_CN),) + interceptors
        srv = NonBlockingGRPCServer(endpoint, tls=self.tls, interceptors=interceptors)
        srv.start(CONTROLLER.registrar(self))
        return srv
