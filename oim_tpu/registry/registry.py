"""The registry service: authz-checked KV plus transparent controller proxy.

≙ reference pkg/oim-registry/registry.go:

- ``SetValue``/``GetValues`` with CommonName authorization
  (registry.go:84-145): ``user.admin`` may set anything; ``controller.<id>``
  only its own ``<id>/address``.
- Transparent proxying of every non-Registry method to the controller named
  by the ``controllerid`` request metadata (registry.go:147-210 +
  ``proxy.TransparentHandler``): frames pass through un-deserialized; the
  proxy dials the controller per call with TLS peer pinned to
  ``controller.<id>`` and closes the connection when the call ends.
"""

from __future__ import annotations

import threading
from typing import Iterator

import grpc

from oim_tpu import log
from oim_tpu.common import endpoint as ep
from oim_tpu.common import metrics, pathutil, tracing
from oim_tpu.common.chancache import ChannelCache, RECONNECT_OPTIONS
from oim_tpu.common.interceptors import LogServerInterceptor
from oim_tpu.common.server import NonBlockingGRPCServer
from oim_tpu.common.tlsconfig import TLSConfig, peer_common_name
from oim_tpu.registry import authz
from oim_tpu.registry.authz import (  # noqa: F401 (re-exported API)
    ADMIN_CN,
    CONTROLLER_CN_PREFIX,
    HOST_CN_PREFIX,
    SERVE_CN_PREFIX,
)
from oim_tpu.registry.db import MemRegistryDB, RegistryDB, _prefix_match
from oim_tpu.spec import REGISTRY, oim_pb2

_ident = lambda b: b


class Registry:
    """gRPC servicer for oim.v1.Registry + proxy director state."""

    def __init__(
        self,
        db: RegistryDB | None = None,
        tls: TLSConfig | None = None,
        proxy_dial_timeout: float = 10.0,
        max_watchers: int = 256,
    ) -> None:
        self.db = db if db is not None else MemRegistryDB()
        self.tls = tls
        self.proxy_dial_timeout = proxy_dial_timeout
        self.max_watchers = max_watchers
        self._watchers = 0
        self._watchers_lock = threading.Lock()
        # Proxy channels are reused across calls keyed on the controller's
        # *registered address* — a re-registration at a new address
        # re-dials, so the reference's dial-per-call routing behavior
        # (registry.go:186-210) is preserved without its handshake cost.
        self._proxy_channels = ChannelCache()
        self._proxied = metrics.registry().counter(
            "oim_registry_proxied_total",
            "Calls forwarded through the transparent proxy.",
            ("controller",),
        )
        self._keys_gauge = metrics.registry().gauge(
            "oim_registry_keys", "Rows in the registry KV store."
        )
        self._keys_cb = lambda: len(self.db.keys(""))
        self._keys_gauge.set_function(self._keys_cb)
        # ONE watch on the local DB feeds everything event-driven in this
        # process: proxy-channel invalidation AND every WatchValues
        # stream's queue (the shared dispatcher below).  Per-watcher DB
        # subscriptions would mean N etcd Watch streams for N gRPC
        # watchers on an etcd-backed registry; the dispatcher keeps that
        # at exactly one no matter the fleet size.
        self._subs_lock = threading.Lock()
        self._subs: dict[int, tuple[str, object]] = {}  # id → (prefix, queue)
        self._sub_seq = 0
        self._cancel_watch = None
        if hasattr(self.db, "watch"):
            self._cancel_watch = self.db.watch("", self._on_db_event)

    def _on_db_event(self, path: str, value: str) -> None:
        self._on_address_event(path, value)
        with self._subs_lock:
            subs = list(self._subs.values())
        for prefix, events in subs:
            if _prefix_match(path, prefix):
                events.put((path, value))

    def _on_address_event(self, path: str, value: str) -> None:
        # Only deletions (explicit or lease expiry) invalidate: an address
        # CHANGE already re-dials via the cache's fingerprint key, and a
        # heartbeat re-put of the same address must NOT churn a healthy
        # cached channel.
        parts = path.split("/")
        if len(parts) == 2 and parts[1] == "address" and value == "":
            self._proxy_channels.invalidate(parts[0])

    # -- KV service --------------------------------------------------------

    def SetValue(self, request: oim_pb2.SetValueRequest, context) -> oim_pb2.SetValueReply:
        try:
            path = pathutil.clean_path(request.value.path)
        except ValueError as exc:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        self._check_set_allowed(path, context)
        if request.ttl_seconds < 0:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "ttl_seconds must be >= 0"
            )
        self.db.store(
            path,
            request.value.value,
            ttl=request.ttl_seconds if request.ttl_seconds > 0 else None,
        )
        log.current().info(
            "registry set",
            path=path,
            deleted=request.value.value == "",
            ttl=request.ttl_seconds or None,
        )
        return oim_pb2.SetValueReply()

    def GetValues(self, request: oim_pb2.GetValuesRequest, context) -> oim_pb2.GetValuesReply:
        prefix = ""
        if request.path:
            try:
                prefix = pathutil.clean_path(request.path)
            except ValueError as exc:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        reply = oim_pb2.GetValuesReply()
        for key, value in self.db.items(prefix):
            reply.values.add(path=key, value=value)
        return reply

    def WatchValues(
        self, request: oim_pb2.WatchValuesRequest, context
    ) -> Iterator[oim_pb2.WatchValuesReply]:
        """Stream mutations under a prefix (value "" = deleted).  All
        streams share ONE DB watch (the dispatcher registered in
        ``__init__``) that fans events out to per-stream queues — N gRPC
        watchers cost the backing store exactly one subscription (one
        etcd Watch stream on an etcd-backed registry, not N).  The
        stream's queue is subscribed BEFORE the initial snapshot, and
        the snapshot ends with an ``initial_done`` marker, so a client
        that reconciles at the marker and applies every later event
        misses nothing (a duplicate reply is possible and harmless —
        watchers are reconcilers, not counters).

        Each stream still pins one server worker thread for its
        lifetime (sync gRPC consumes the response generator on a pool
        thread), so concurrent watchers are capped: the server pool is
        sized ``max_watchers + 16`` and beyond ``max_watchers`` the
        call fails RESOURCE_EXHAUSTED and the client degrades to
        GetValues polling — discovery gets slower, the registry stays
        alive.  Threads are the bound and they are configuration-bounded,
        not fleet-bounded."""
        import queue as _queue

        prefix = ""
        if request.path:
            try:
                prefix = pathutil.clean_path(request.path)
            except ValueError as exc:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        if self._cancel_watch is None:
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "registry database does not support watch",
            )
        with self._watchers_lock:
            if self._watchers >= self.max_watchers:
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"watcher limit ({self.max_watchers}) reached; "
                    "poll GetValues instead",
                )
            self._watchers += 1
        # From here on every early exit (including an exception while
        # subscribing or snapshotting) must release the watcher slot —
        # a leaked slot is permanent and eventually forces the whole
        # fleet to RESOURCE_EXHAUSTED polling.
        sub_id = None
        try:
            events: "_queue.Queue[tuple[str, str]]" = _queue.Queue()
            with self._subs_lock:
                sub_id = self._sub_seq
                self._sub_seq += 1
                self._subs[sub_id] = (prefix, events)

            def unsubscribe(sid=sub_id):
                with self._subs_lock:
                    self._subs.pop(sid, None)

            context.add_callback(unsubscribe)
            if request.send_initial:
                for key, value in self.db.items(prefix):
                    yield oim_pb2.WatchValuesReply(
                        value=oim_pb2.Value(path=key, value=value)
                    )
                yield oim_pb2.WatchValuesReply(initial_done=True)
            while context.is_active():
                try:
                    path, value = events.get(timeout=0.5)
                except _queue.Empty:
                    continue
                yield oim_pb2.WatchValuesReply(
                    value=oim_pb2.Value(path=path, value=value)
                )
        finally:
            if sub_id is not None:
                with self._subs_lock:
                    self._subs.pop(sub_id, None)
            with self._watchers_lock:
                self._watchers -= 1

    def _check_set_allowed(self, path: str, context) -> None:
        """CN-based write authorization (≙ registry.go:100-109).

        The allow/deny decision is the declarative grant table in
        oim_tpu/registry/authz.py — the same table the ``authz-coverage``
        lint pass checks every write site against, so enforcement and the
        static gate can never drift.  Unauthenticated (insecure server,
        e.g. tests) means no restrictions, matching the reference's
        behavior without TLS configured.  Only the denial *messages* live
        here, phrased per identity class.
        """
        cn = peer_common_name(context)
        if authz.set_allowed(cn, path):
            return
        if cn.startswith(CONTROLLER_CN_PREFIX):
            controller_id = cn[len(CONTROLLER_CN_PREFIX):]
            context.abort(
                grpc.StatusCode.PERMISSION_DENIED,
                f"{cn!r} may only set {controller_id}/address, "
                f"health/{controller_id}/* or events/{cn}/*",
            )
        if cn.startswith(SERVE_CN_PREFIX):
            serve_id = cn[len(SERVE_CN_PREFIX):]
            context.abort(
                grpc.StatusCode.PERMISSION_DENIED,
                f"{cn!r} may only set serve/{serve_id}/address",
            )
        if cn.startswith(HOST_CN_PREFIX):
            host_id = cn[len(HOST_CN_PREFIX):]
            context.abort(
                grpc.StatusCode.PERMISSION_DENIED,
                f"{cn!r} may only set volumes/*/hosts/{host_id} "
                "or volumes/*/coordinator",
            )
        context.abort(
            grpc.StatusCode.PERMISSION_DENIED,
            f"{cn!r} is not allowed to set registry values",
        )

    # -- Transparent proxy -------------------------------------------------

    def _proxy_authz(self, controller_id: str, context) -> None:
        """Only ``host.<id>`` (the node agent for that controller) and the
        admin may reach controller ``<id>`` (≙ registry.go:174-184)."""
        cn = peer_common_name(context)
        if cn is None or cn == ADMIN_CN:
            return
        if cn == f"{HOST_CN_PREFIX}{controller_id}":
            return
        context.abort(
            grpc.StatusCode.PERMISSION_DENIED,
            f"{cn!r} may not call controller {controller_id!r}",
        )

    def _connect(self, controller_id: str, context) -> grpc.Channel:
        """Resolve ``<id>/address`` and dial the controller, pinning its CN
        (≙ streamDirector.Connect, registry.go:186-203)."""
        address = self.db.lookup(f"{controller_id}/address")
        if not address:
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"no address registered for controller {controller_id!r}",
            )
        target = ep.parse(address).grpc_target()
        # A moved controller re-registers at a new address → fingerprint
        # change → re-dial; a *restarted* controller at the same address
        # is handled by gRPC's own reconnect (bounded by
        # RECONNECT_OPTIONS), so no invalidation path is needed.
        if self.tls is not None:
            tls = self.tls.with_peer(f"{CONTROLLER_CN_PREFIX}{controller_id}")
            return self._proxy_channels.get(
                controller_id,
                (target, tls.ca_pem, tls.cert_pem, tls.key_pem),
                lambda: tracing.trace_channel(
                    grpc.secure_channel(
                        target,
                        tls.channel_credentials(),
                        options=tls.channel_options() + RECONNECT_OPTIONS,
                    ),
                    "oim-registry",
                ),
            )
        return self._proxy_channels.get(
            controller_id,
            (target, None),
            lambda: tracing.trace_channel(
                grpc.insecure_channel(target, options=RECONNECT_OPTIONS),
                "oim-registry",
            ),
        )

    def _proxy_behavior(self, method: str):
        def behavior(request_iterator, context) -> Iterator[bytes]:
            metadata = dict(context.invocation_metadata())
            controller_id = metadata.get("controllerid")
            if not controller_id:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"unknown method {method} without controllerid metadata",
                )
            self._proxy_authz(controller_id, context)
            with log.with_fields(method=method, controllerid=controller_id):
                log.current().debug("proxying")
                self._proxied.inc(controller_id)
                channel = self._connect(controller_id, context)
                call = channel.stream_stream(
                    method,
                    request_serializer=_ident,
                    response_deserializer=_ident,
                )(
                    request_iterator,
                    timeout=context.time_remaining(),
                    metadata=context.invocation_metadata(),
                )
                try:
                    yield from call
                except grpc.RpcError as exc:
                    # Surface the controller's status verbatim to the caller.
                    context.abort(exc.code(), exc.details())
                finally:
                    # No-op after normal completion; when the downstream
                    # caller cancels or disconnects mid-stream (this
                    # generator is closed), the in-flight upstream call
                    # must not keep running against the controller.  The
                    # per-call-channel version got this for free from
                    # channel.close().
                    call.cancel()

        return behavior

    def proxy_handler(self) -> grpc.GenericRpcHandler:
        """Generic handler forwarding any non-Registry method."""
        registry_prefix = f"/{REGISTRY.full_name}/"
        proxy = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                method = handler_call_details.method
                if method.startswith(registry_prefix):
                    return None
                return grpc.stream_stream_rpc_method_handler(
                    proxy._proxy_behavior(method),
                    request_deserializer=_ident,
                    response_serializer=_ident,
                )

        return Handler()

    # -- Serving -----------------------------------------------------------

    def registrar(self):
        """Registrar wiring the KV service plus the transparent proxy
        (≙ registry.Server wiring, registry.go:248-261)."""

        def register(server: grpc.Server) -> None:
            REGISTRY.registrar(self)(server)
            server.add_generic_rpc_handlers((self.proxy_handler(),))

        return register

    def start_server(
        self, endpoint: str, interceptors: tuple = ()
    ) -> NonBlockingGRPCServer:
        srv = NonBlockingGRPCServer(
            endpoint,
            tls=self.tls,
            interceptors=interceptors
            or (
                tracing.TraceServerInterceptor("oim-registry"),
                metrics.MetricsServerInterceptor("oim-registry"),
                LogServerInterceptor(),
            ),
            # Each WatchValues stream pins a worker for its lifetime
            # (sync gRPC); size the pool so a full house of watchers
            # still leaves headroom for KV calls and proxied traffic.
            max_workers=self.max_watchers + 16,
        )
        srv.start(self.registrar())
        return srv

    def close(self) -> None:
        """Release cached proxy channels and deregister gauges (embedders
        that stop/start many registries in one process; a daemon just
        exits)."""
        if self._cancel_watch is not None:
            self._cancel_watch()
        self._proxy_channels.close()
        self._keys_gauge.remove(fn=self._keys_cb)
