"""Cluster registry: KV store + transparent gRPC proxy (≙ pkg/oim-registry)."""

from oim_tpu.registry.db import MemRegistryDB, RegistryDB, SqliteRegistryDB
from oim_tpu.registry.etcd import EtcdKVServer, EtcdRegistryDB
from oim_tpu.registry.registry import Registry

__all__ = [
    "Registry",
    "RegistryDB",
    "MemRegistryDB",
    "SqliteRegistryDB",
    "EtcdRegistryDB",
    "EtcdKVServer",
]
