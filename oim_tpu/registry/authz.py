"""Declarative registry write-authorization: ONE table, two consumers.

The reference encoded who-may-set-what as inline conditionals
(reference pkg/oim-registry/registry.go:100-109); as this repo grew
health/, events/, serve/ and volumes/ keyspaces, those conditionals
became the de-facto security policy of the whole control plane — and
nothing machine-checked that every code path *writing* a key actually
had a grant here.  This module makes the policy a data table:

- ``Registry._check_set_allowed`` (registry.py) drives its allow/deny
  decision off :func:`set_allowed`, so enforcement IS the table;
- the ``authz-coverage`` pass of ``tools/oimlint`` cross-checks every
  registry-write site in the tree against :data:`AUTHZ_GRANTS`, so a
  new ``put`` path without a grant fails lint before it fails with
  PERMISSION_DENIED in production.

Pattern language (one segment per ``/``):

- a literal segment matches itself;
- ``*`` matches any single segment;
- ``{id}`` matches the identity captured from the CN pattern's
  ``{id}`` (e.g. CN ``controller.c7`` → ``{id}`` = ``c7``);
- ``{cn}`` matches the peer's full CommonName;
- the special path ``**`` matches everything (the admin grant).

CN patterns are either a literal CN (``user.admin``), ``*`` (any
authenticated peer), or ``<prefix>{id}`` (captures the identity).
Stdlib-only and import-light on purpose: the lint pass loads it from
an AST-scanning tool that must stay fast.
"""

from __future__ import annotations

ADMIN_CN = "user.admin"
CONTROLLER_CN_PREFIX = "controller."
HOST_CN_PREFIX = "host."
SERVE_CN_PREFIX = "serve."

# (cn_pattern, path_pattern) — additive: any matching row allows the
# write.  Least-privilege shape throughout: every component may touch
# only its own subtree, so one compromised daemon cannot forge another
# identity's address, health, discovery or flight-recorder history.
AUTHZ_GRANTS: tuple[tuple[str, str], ...] = (
    # The operator writes anything: drain/<cid> cordons,
    # evictions/<vol> remap-clears, <cid>/pci defaults, test fixtures.
    (ADMIN_CN, "**"),
    # Any authenticated component may publish its OWN flight-recorder
    # events (events/<cn>/<seq>, oim_tpu/common/events).
    ("*", "events/{cn}/*"),
    # ... and its OWN load telemetry (load/<cn>, oim_tpu/autoscale/load):
    # a serving instance reports exactly its own pressure — the
    # autoscaler's observation plane — and cannot forge a sibling's.
    ("*", "load/{cn}"),
    # A controller registers its own address and publishes its own
    # chip-health telemetry — never drain/eviction marks (operator or
    # registry-side monitor writes).
    (CONTROLLER_CN_PREFIX + "{id}", "{id}/address"),
    (CONTROLLER_CN_PREFIX + "{id}", "health/{id}/*"),
    # A serving instance announces only its own discovery key and its
    # own disaggregation pool role (serve/registration.py — the
    # router/autoscaler partition the fleet on it; forging a sibling's
    # role would mis-route its traffic class).
    (SERVE_CN_PREFIX + "{id}", "serve/{id}/address"),
    (SERVE_CN_PREFIX + "{id}", "serve/{id}/pool"),
    # The multi-tenant QoS policy document (qos/tenants,
    # oim_tpu/qos/publish.py): operator-owned.  Redundant with the
    # admin ** wildcard TODAY, but explicit on purpose — the QoS key is
    # fleet-wide security policy (who may consume what), so it gets a
    # named row the wildcard could someday narrow around, and the row
    # the authz-coverage lint pins the publisher module against.
    (ADMIN_CN, "qos/tenants"),
    # A node agent publishes its own multi-host rendezvous entry; any
    # staging host may commit the volume's coordinator (the protocol
    # lets only the sort-first one actually do it, but the registry
    # cannot know the sort without reading volume state).
    (HOST_CN_PREFIX + "{id}", "volumes/*/hosts/{id}"),
    (HOST_CN_PREFIX + "{id}", "volumes/*/coordinator"),
)

_NO_MATCH = object()


def _cn_identity(pattern: str, cn: str):
    """The identity ``{id}`` captures for ``cn`` under ``pattern``, or
    ``_NO_MATCH``.  Literal patterns and ``*`` capture no identity
    (return None on match)."""
    if pattern == "*":
        return None
    if "{id}" in pattern:
        prefix = pattern[: pattern.index("{id}")]
        if cn.startswith(prefix) and len(cn) > len(prefix):
            return cn[len(prefix):]
        return _NO_MATCH
    return None if pattern == cn else _NO_MATCH


def _path_matches(pattern: str, path: str, ident, cn: str) -> bool:
    if pattern == "**":
        return True
    pat_segs = pattern.split("/")
    segs = path.split("/")
    if len(pat_segs) != len(segs):
        return False
    for pat, seg in zip(pat_segs, segs):
        if pat == "*":
            continue
        if pat == "{id}":
            if ident is None or seg != ident:
                return False
        elif pat == "{cn}":
            if seg != cn:
                return False
        elif pat != seg:
            return False
    return True


def set_allowed(cn: str | None, path: str) -> bool:
    """May the peer named ``cn`` write ``path``?  ``cn is None`` means an
    unauthenticated (insecure, e.g. test) server: no restrictions,
    matching the reference's behavior without TLS configured."""
    if cn is None:
        return True
    for cn_pattern, path_pattern in AUTHZ_GRANTS:
        ident = _cn_identity(cn_pattern, cn)
        if ident is _NO_MATCH:
            continue
        if _path_matches(path_pattern, path, ident, cn):
            return True
    return False
