"""Registry database backends.

≙ reference pkg/oim-registry/registry.go:31-41 (the 3-method ``RegistryDB``
seam) and memdb.go:15-52 (the mutex-guarded in-memory map).  The reference
planned an etcd backend behind this seam but never implemented it (reference
README.md:131-135); here the durable backend is SQLite (WAL mode), which the
image ships, giving the registry crash-safe state for multi-host deployments
(BASELINE.json config 5) without an external service.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Protocol


class RegistryDB(Protocol):
    def store(self, path: str, value: str) -> None:
        """Set ``path`` to ``value``; an empty value deletes the key."""
        ...

    def lookup(self, path: str) -> str:
        """Value at ``path``, or "" when absent."""
        ...

    def keys(self, prefix: str) -> list[str]:
        """All keys equal to or under ``prefix`` ("" lists everything)."""
        ...

    def items(self, prefix: str) -> list[tuple[str, str]]:
        """Sorted (path, value) pairs at or under ``prefix``, read atomically."""
        ...


def _prefix_match(key: str, prefix: str) -> bool:
    if prefix == "":
        return True
    return key == prefix or key.startswith(prefix + "/")


def _like_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace("%", "\\%").replace("_", "\\_")


class MemRegistryDB:
    """In-memory backend (≙ memRegistryDB, reference memdb.go:21-52)."""

    def __init__(self) -> None:
        self._data: dict[str, str] = {}
        self._lock = threading.Lock()

    def store(self, path: str, value: str) -> None:
        with self._lock:
            if value == "":
                self._data.pop(path, None)
            else:
                self._data[path] = value

    def lookup(self, path: str) -> str:
        with self._lock:
            return self._data.get(path, "")

    def keys(self, prefix: str) -> list[str]:
        with self._lock:
            return sorted(k for k in self._data if _prefix_match(k, prefix))

    def items(self, prefix: str) -> list[tuple[str, str]]:
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items() if _prefix_match(k, prefix)
            )


class SqliteRegistryDB:
    """Durable backend filling the seam the reference reserved for etcd."""

    def __init__(self, path: str) -> None:
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (path TEXT PRIMARY KEY, value TEXT)"
            )
            self._conn.commit()

    def store(self, path: str, value: str) -> None:
        with self._lock:
            if value == "":
                self._conn.execute("DELETE FROM kv WHERE path = ?", (path,))
            else:
                self._conn.execute(
                    "INSERT INTO kv (path, value) VALUES (?, ?) "
                    "ON CONFLICT(path) DO UPDATE SET value = excluded.value",
                    (path, value),
                )
            self._conn.commit()

    def lookup(self, path: str) -> str:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE path = ?", (path,)
            ).fetchone()
        return row[0] if row else ""

    def keys(self, prefix: str) -> list[str]:
        return [k for k, _ in self.items(prefix)]

    def items(self, prefix: str) -> list[tuple[str, str]]:
        with self._lock:
            if prefix == "":
                rows = self._conn.execute(
                    "SELECT path, value FROM kv ORDER BY path"
                ).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT path, value FROM kv WHERE path = ? "
                    "OR path LIKE ? ESCAPE '\\' ORDER BY path",
                    (prefix, _like_escape(prefix) + "/%"),
                ).fetchall()
        return [(r[0], r[1]) for r in rows]

    def close(self) -> None:
        with self._lock:
            self._conn.close()
