"""Registry database backends.

≙ reference pkg/oim-registry/registry.go:31-41 (the 3-method ``RegistryDB``
seam) and memdb.go:15-52 (the mutex-guarded in-memory map).  The reference
planned an etcd backend behind this seam but never implemented it (reference
README.md:131-135); here the durable backend is SQLite (WAL mode), which the
image ships, giving the registry crash-safe state for multi-host deployments
(BASELINE.json config 5) without an external service.

Beyond the reference's seam, every backend supports two liveness
primitives the production HA story needs (and the etcd API was designed
around):

- ``watch(prefix, callback)`` — event-driven change notification; the
  registry's WatchValues stream and the serving router's discovery ride
  this instead of polling, so a deleted backend key propagates in
  milliseconds, not at the next poll tick.
- ``store(path, value, ttl=...)`` — leased keys: the key auto-deletes
  ``ttl`` seconds after the last store that carried it.  Heartbeat
  registration (controller/serve addresses) uses this so a crashed
  writer's address *expires* with a watch event instead of surviving
  until its slot is overwritten.

The local backends (Mem/Sqlite) implement both in-process — correct
because exactly one registry process owns the store (the SQLite file is
registry-private state, not shared).  The etcd backend
(registry/etcd.py) delegates to real etcd Watch/Lease, which extends the
same semantics across registry replicas.
"""

from __future__ import annotations

import heapq
import sqlite3
import threading
import time
from typing import Callable, Protocol

from oim_tpu.common import metrics

WatchCallback = Callable[[str, str], None]  # (path, value); "" = deleted

# Lease expiries were invisible before this counter: a fleet where
# controllers silently drop off (addresses expiring, health subtrees
# vanishing) now shows up on /metrics instead of only in effect.  Counts
# keys actually deleted by the sweep — a stale expiry losing the refresh
# race does not count.
LEASE_EXPIRATIONS = metrics.registry().counter(
    "oim_registry_lease_expirations_total",
    "Leased registry keys deleted by the lease sweep (TTL ran out).",
)


class RegistryDB(Protocol):
    def store(self, path: str, value: str, *, ttl: float | None = None) -> None:
        """Set ``path`` to ``value``; an empty value deletes the key.
        ``ttl`` (seconds) leases the key: it auto-deletes that long after
        the LAST store that carried a ttl, unless refreshed; ``None``
        makes the key persistent (and clears any prior lease)."""
        ...

    def lookup(self, path: str) -> str:
        """Value at ``path``, or "" when absent."""
        ...

    def keys(self, prefix: str) -> list[str]:
        """All keys equal to or under ``prefix`` ("" lists everything)."""
        ...

    def items(self, prefix: str) -> list[tuple[str, str]]:
        """Sorted (path, value) pairs at or under ``prefix``, read atomically."""
        ...

    def watch(self, prefix: str, callback: WatchCallback) -> Callable[[], None]:
        """Invoke ``callback(path, value)`` on every mutation at or under
        ``prefix`` (value "" = deletion, including lease expiry).  Returns
        a cancel function.  Callbacks run on internal threads and must not
        block."""
        ...


def _prefix_match(key: str, prefix: str) -> bool:
    if prefix == "":
        return True
    return key == prefix or key.startswith(prefix + "/")


def _like_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace("%", "\\%").replace("_", "\\_")


class _EventHub:
    """Watch fan-out for the single-process backends.

    Delivery ORDER equals commit order: the owner enqueues under ITS
    data lock (``enqueue``), so the queue sequence is the mutation
    sequence, and a single drainer at a time (``dispatch``) delivers.
    Without this, two racing stores to one key could reach watchers
    reversed — and with event-driven discovery there is no steady-state
    poll left to heal a diverged watcher view.  Callbacks run outside
    the owner's data lock (a callback may re-enter the DB) and must not
    block."""

    def __init__(self) -> None:
        self._sub_lock = threading.Lock()
        self._subs: dict[int, tuple[str, WatchCallback]] = {}
        self._next = 0
        self._q_lock = threading.Lock()
        self._queue: list[tuple[str, str]] = []
        self._draining = False

    def subscribe(
        self, prefix: str, callback: WatchCallback
    ) -> Callable[[], None]:
        with self._sub_lock:
            sid = self._next
            self._next += 1
            self._subs[sid] = (prefix, callback)

        def cancel() -> None:
            with self._sub_lock:
                self._subs.pop(sid, None)

        return cancel

    def enqueue(self, path: str, value: str) -> None:
        """Record one mutation; MUST be called while holding the owner's
        data lock so queue order is commit order."""
        with self._q_lock:
            self._queue.append((path, value))

    def dispatch(self) -> None:
        """Deliver queued events; call AFTER releasing the data lock.
        One drainer at a time — a concurrent (or re-entrant, via a
        callback that stores) dispatch returns immediately and an
        active or subsequent drainer picks its events up, preserving
        order.  The outer loop re-checks after releasing the draining
        flag, so an event enqueued while the flag was still set can
        never strand."""
        while True:
            with self._q_lock:
                if self._draining or not self._queue:
                    return
                self._draining = True
            try:
                while True:
                    with self._q_lock:
                        if not self._queue:
                            break
                        path, value = self._queue.pop(0)
                    with self._sub_lock:
                        targets = [
                            cb
                            for pfx, cb in self._subs.values()
                            if _prefix_match(path, pfx)
                        ]
                    for cb in targets:
                        cb(path, value)
            finally:
                with self._q_lock:
                    self._draining = False


class _LeaseSweeper:
    """One lazy daemon thread expiring leased keys at their deadlines.

    ``arm(path, deadline)`` schedules (or re-schedules) a key;
    ``disarm(path)`` makes it persistent again.  Both bump the path's
    SEQUENCE; at a deadline the sweeper calls ``expire(path, seq)`` with
    the sequence captured at arm time.  The owner must re-check
    ``still_current(path, seq)`` under ITS OWN data lock before deleting
    — that closes the refresh race end-to-end: a store that completed
    after the deadline fired bumped the sequence (arm/disarm run under
    the owner's data lock), so the stale expiry is a no-op, and a store
    blocked on the data lock runs after the expiry and rewrites the key.

    Lock order everywhere: owner data lock → sweeper condition.  The
    sweeper itself calls ``expire`` holding NEITHER.
    """

    def __init__(self, expire: Callable[[str, int], None]) -> None:
        self._expire = expire
        self._cond = threading.Condition()
        self._seq: dict[str, int] = {}
        self._entries: dict[str, tuple[float, int]] = {}
        self._heap: list[tuple[float, str, int]] = []
        self._thread: threading.Thread | None = None
        self._closed = False

    def arm(self, path: str, deadline: float) -> None:
        with self._cond:
            seq = self._seq.get(path, 0) + 1
            self._seq[path] = seq
            self._entries[path] = (deadline, seq)
            heapq.heappush(self._heap, (deadline, path, seq))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="registry-lease-sweep"
                )
                self._thread.start()
            self._cond.notify()

    def disarm(self, path: str) -> None:
        with self._cond:
            if path in self._entries or path in self._seq:
                self._seq[path] = self._seq.get(path, 0) + 1
            self._entries.pop(path, None)
            # Stale heap entries are skipped in _run (seq mismatch).

    def still_current(self, path: str, seq: int) -> bool:
        """True iff no arm/disarm happened since ``seq`` was issued.
        Call under the owner's data lock to make the expiry decision
        atomic with the owner's mutations."""
        with self._cond:
            return self._seq.get(path) == seq

    def close(self) -> None:
        with self._cond:
            self._closed = True
            thread = self._thread
            self._cond.notify()
        # Join OUTSIDE the condition so an in-flight expire (which
        # re-enters the owner's store and may need the condition for
        # still_current) can finish; only then may the owner release its
        # own resources (e.g. close the SQLite connection).
        if thread is not None:
            thread.join(timeout=10)

    def _run(self) -> None:
        while True:
            with self._cond:
                if self._closed:
                    return
                now = time.monotonic()
                due: list[tuple[str, int]] = []
                while self._heap and self._heap[0][0] <= now:
                    deadline, path, seq = heapq.heappop(self._heap)
                    # Only the CURRENT entry counts: refreshed/disarmed
                    # keys leave stale heap entries behind.
                    if self._entries.get(path) == (deadline, seq):
                        del self._entries[path]
                        due.append((path, seq))
                if not due:
                    wait = (
                        self._heap[0][0] - now if self._heap else None
                    )
                    self._cond.wait(timeout=wait)
                    continue
            for path, seq in due:  # outside the lock: expire re-enters store
                self._expire(path, seq)


class MemRegistryDB:
    """In-memory backend (≙ memRegistryDB, reference memdb.go:21-52)."""

    def __init__(self) -> None:
        self._data: dict[str, str] = {}
        self._lock = threading.Lock()
        self._hub = _EventHub()
        self._sweeper = _LeaseSweeper(self._expire)

    def store(self, path: str, value: str, *, ttl: float | None = None) -> None:
        with self._lock:
            if value == "":
                existed = self._data.pop(path, None) is not None
                changed = existed
            else:
                self._data[path] = value
                changed = True
            # Arm/disarm under the data lock: the sequence bump is what
            # defeats a stale expiry racing this store (see _LeaseSweeper).
            if value == "" or ttl is None:
                self._sweeper.disarm(path)
            else:
                self._sweeper.arm(path, time.monotonic() + ttl)
            # Enqueue under the lock too: event order = commit order.
            if changed:
                self._hub.enqueue(path, value)
        self._hub.dispatch()

    def _expire(self, path: str, seq: int) -> None:
        with self._lock:
            if not self._sweeper.still_current(path, seq):
                return  # a store since the deadline fired wins
            existed = self._data.pop(path, None) is not None
            if existed:
                self._hub.enqueue(path, "")
        if existed:
            LEASE_EXPIRATIONS.inc()
        self._hub.dispatch()

    def lookup(self, path: str) -> str:
        with self._lock:
            return self._data.get(path, "")

    def keys(self, prefix: str) -> list[str]:
        with self._lock:
            return sorted(k for k in self._data if _prefix_match(k, prefix))

    def items(self, prefix: str) -> list[tuple[str, str]]:
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items() if _prefix_match(k, prefix)
            )

    def watch(self, prefix: str, callback: WatchCallback) -> Callable[[], None]:
        return self._hub.subscribe(prefix, callback)

    def close(self) -> None:
        self._sweeper.close()


class SqliteRegistryDB:
    """Durable backend filling the seam the reference reserved for etcd.

    Leases survive a registry restart: deadlines are stored as an
    absolute wall-clock column and re-armed on open, so a key whose
    writer died while the registry was down still expires.  Watch events
    are in-process (exactly one registry process owns the file)."""

    def __init__(self, path: str) -> None:
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._hub = _EventHub()
        self._sweeper = _LeaseSweeper(self._expire)
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (path TEXT PRIMARY KEY, value TEXT)"
            )
            cols = [
                r[1]
                for r in self._conn.execute("PRAGMA table_info(kv)").fetchall()
            ]
            if "expires_at" not in cols:  # pre-lease schema migration
                self._conn.execute("ALTER TABLE kv ADD COLUMN expires_at REAL")
            self._conn.commit()
            rows = self._conn.execute(
                "SELECT path, expires_at FROM kv WHERE expires_at IS NOT NULL"
            ).fetchall()
        now_wall, now_mono = time.time(), time.monotonic()
        for key, expires_at in rows:
            self._sweeper.arm(key, now_mono + max(0.0, expires_at - now_wall))

    def store(self, path: str, value: str, *, ttl: float | None = None) -> None:
        expires_at = time.time() + ttl if ttl is not None else None
        with self._lock:
            if value == "":
                cur = self._conn.execute(
                    "DELETE FROM kv WHERE path = ?", (path,)
                )
                changed = cur.rowcount > 0
            else:
                self._conn.execute(
                    "INSERT INTO kv (path, value, expires_at) VALUES (?, ?, ?) "
                    "ON CONFLICT(path) DO UPDATE SET value = excluded.value, "
                    "expires_at = excluded.expires_at",
                    (path, value, expires_at),
                )
                changed = True
            self._conn.commit()
            # Under the data lock — see MemRegistryDB.store.
            if value == "" or ttl is None:
                self._sweeper.disarm(path)
            else:
                self._sweeper.arm(path, time.monotonic() + ttl)
            if changed:
                self._hub.enqueue(path, value)
        self._hub.dispatch()

    def _expire(self, path: str, seq: int) -> None:
        with self._lock:
            if not self._sweeper.still_current(path, seq):
                return  # a store since the deadline fired wins
            cur = self._conn.execute("DELETE FROM kv WHERE path = ?", (path,))
            existed = cur.rowcount > 0
            self._conn.commit()
            if existed:
                self._hub.enqueue(path, "")
        if existed:
            LEASE_EXPIRATIONS.inc()
        self._hub.dispatch()

    def lookup(self, path: str) -> str:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE path = ?", (path,)
            ).fetchone()
        return row[0] if row else ""

    def keys(self, prefix: str) -> list[str]:
        return [k for k, _ in self.items(prefix)]

    def items(self, prefix: str) -> list[tuple[str, str]]:
        with self._lock:
            if prefix == "":
                rows = self._conn.execute(
                    "SELECT path, value FROM kv ORDER BY path"
                ).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT path, value FROM kv WHERE path = ? "
                    "OR path LIKE ? ESCAPE '\\' ORDER BY path",
                    (prefix, _like_escape(prefix) + "/%"),
                ).fetchall()
        return [(r[0], r[1]) for r in rows]

    def watch(self, prefix: str, callback: WatchCallback) -> Callable[[], None]:
        return self._hub.subscribe(prefix, callback)

    def close(self) -> None:
        self._sweeper.close()
        with self._lock:
            self._conn.close()
