"""Etcd-backed RegistryDB + an in-process etcd-compatible KV server.

Fills the seam the reference reserved for etcd but never implemented
(reference pkg/oim-registry/registry.go:31-41 — "behind the RegistryDB
interface"; README.md:131-135).  ``EtcdRegistryDB`` is a client of the
etcd v3 gRPC API (proto/etcd/rpc.proto: the KV Range/Put/DeleteRange
subset plus Watch and Lease Grant/Revoke/KeepAlive), so a production
registry can point at a real etcd cluster for replicated durable state
(BASELINE.json config 5: N controllers behind an etcd-backed registry).
``EtcdKVServer`` serves the same wire subset from a local ``RegistryDB``
— the test double, and a single-binary option.

Liveness semantics (the production HA story):

- ``store(path, value, ttl=N)`` attaches the key to ONE cached
  N-second lease per (key, ttl); each heartbeat refreshes that lease
  with a LeaseKeepAlive round-trip and re-Puts, re-granting only when
  the keepalive reports the lease gone (the etcd-recommended pattern —
  a grant is a raft write, so per-heartbeat grants would be lease churn
  in raft state).  A crashed writer's key is deleted by etcd when its
  lease expires — with a DELETE watch event — instead of its stale
  address surviving until overwritten.
- ``watch(prefix, callback)`` opens a Watch stream and invokes the
  callback per event; the stream auto-reopens after transient failures
  (same never-die stance as the controller heartbeat).

Registry paths map to etcd keys as ``<namespace><path>`` (default
namespace ``/oim/``).  Prefix queries use etcd's range convention
[key, successor(key)) and re-filter on path-segment boundaries, since a
byte prefix also matches sibling keys like ``foo-bar`` for prefix ``foo``.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Iterator

import grpc

from oim_tpu import log
from oim_tpu.registry.db import (
    MemRegistryDB,
    RegistryDB,
    WatchCallback,
    _LeaseSweeper,
    _prefix_match,
)
from oim_tpu.spec.gen.etcd import rpc_pb2
from oim_tpu.spec.rpc import BIDI_STREAM, ServiceSpec

ETCD_KV = ServiceSpec(
    "etcdserverpb.KV",
    {
        "Range": (rpc_pb2.RangeRequest, rpc_pb2.RangeResponse),
        "Put": (rpc_pb2.PutRequest, rpc_pb2.PutResponse),
        "DeleteRange": (rpc_pb2.DeleteRangeRequest, rpc_pb2.DeleteRangeResponse),
    },
)

ETCD_WATCH = ServiceSpec(
    "etcdserverpb.Watch",
    {
        "Watch": (rpc_pb2.WatchRequest, rpc_pb2.WatchResponse, BIDI_STREAM),
    },
)

ETCD_LEASE = ServiceSpec(
    "etcdserverpb.Lease",
    {
        "LeaseGrant": (rpc_pb2.LeaseGrantRequest, rpc_pb2.LeaseGrantResponse),
        "LeaseRevoke": (rpc_pb2.LeaseRevokeRequest, rpc_pb2.LeaseRevokeResponse),
        "LeaseKeepAlive": (
            rpc_pb2.LeaseKeepAliveRequest,
            rpc_pb2.LeaseKeepAliveResponse,
            BIDI_STREAM,
        ),
    },
)

DEFAULT_NAMESPACE = "/oim/"


def _successor(key: bytes) -> bytes:
    """etcd prefix range end: the key with its last byte incremented
    (keys are namespace-prefixed and non-empty, and the namespace contains
    no 0xff bytes, so no carry handling is needed)."""
    return key[:-1] + bytes([key[-1] + 1])


class EtcdRegistryDB:
    """RegistryDB speaking the etcd v3 KV API.

    One persistent channel (etcd client convention), with a single
    reconnect retry per call so a restarted etcd member doesn't require a
    registry restart — the same per-operation resilience stance as the
    rest of the control plane.
    """

    def __init__(
        self,
        endpoint: str,
        namespace: str = DEFAULT_NAMESPACE,
        credentials: grpc.ChannelCredentials | None = None,
        timeout: float = 10.0,
        channel_factory: Callable[[], grpc.Channel] | None = None,
    ) -> None:
        self.endpoint = endpoint
        self.namespace = namespace
        self.timeout = timeout
        self._credentials = credentials
        self._channel_factory = channel_factory or self._dial
        self._lock = threading.Lock()
        self._channel: grpc.Channel | None = None
        self._closed = False
        self._watch_cancels: set = set()
        # (path, ttl_seconds) → live lease id.  Leased stores refresh this
        # lease via LeaseKeepAlive instead of granting a new one per
        # heartbeat — against a real etcd cluster a grant is a raft write,
        # so per-heartbeat grants are ttl-proportional lease churn in raft
        # state (the etcd-recommended pattern is one lease + KeepAlive).
        self._lease_cache: dict[tuple[str, int], int] = {}

    def _dial(self) -> grpc.Channel:
        from oim_tpu.common import endpoint as ep
        from oim_tpu.common.regdial import KEEPALIVE_OPTIONS

        target = ep.parse(self.endpoint).grpc_target()
        # Keepalive: the Watch stream idles for hours on a quiet fleet;
        # pings surface silently-dropped connections as RpcErrors the
        # reopen loop can handle.
        if self._credentials is not None:
            return grpc.secure_channel(
                target, self._credentials, options=KEEPALIVE_OPTIONS
            )
        return grpc.insecure_channel(target, options=KEEPALIVE_OPTIONS)

    def _channel_get(self) -> grpc.Channel:
        """THE lazy-create path for the shared persistent channel."""
        with self._lock:
            if self._closed:
                raise RuntimeError("EtcdRegistryDB is closed")
            if self._channel is None:
                self._channel = self._channel_factory()
            return self._channel

    def _reset(self) -> None:
        with self._lock:
            if self._channel is not None:
                try:
                    self._channel.close()
                except Exception:
                    pass
                self._channel = None

    def _call(self, fn):
        """Run ``fn(channel)`` with one reconnect retry on UNAVAILABLE."""
        try:
            return fn(self._channel_get())
        except grpc.RpcError as exc:
            if exc.code() != grpc.StatusCode.UNAVAILABLE:
                raise
            log.current().warning(
                "etcd unavailable; redialing", endpoint=self.endpoint
            )
            self._reset()
            return fn(self._channel_get())

    def _key(self, path: str) -> bytes:
        return (self.namespace + path).encode()

    # -- RegistryDB --------------------------------------------------------

    def store(self, path: str, value: str, *, ttl: float | None = None) -> None:
        if value == "":
            with self._lock:
                for ck in [k for k in self._lease_cache if k[0] == path]:
                    del self._lease_cache[ck]
            self._call(
                lambda ch: ETCD_KV.stub(ch).DeleteRange(
                    rpc_pb2.DeleteRangeRequest(key=self._key(path)),
                    timeout=self.timeout,
                )
            )
            return
        lease_id = 0
        if ttl is not None:
            # One lease per (key, ttl), refreshed with LeaseKeepAlive on
            # every heartbeat; re-grant only when the keepalive reports the
            # lease gone (TTL 0 — expired during a partition, or server
            # restart).  The liveness contract is unchanged ("key gone TTL
            # after the last refresh") but a steady-state heartbeat is one
            # keepalive + one Put, with zero lease churn in raft state.
            ttl_s = max(1, math.ceil(ttl))
            cache_key = (path, ttl_s)
            with self._lock:
                lease_id = self._lease_cache.get(cache_key, 0)
            if lease_id:
                try:
                    if self.keepalive_once(lease_id) <= 0:
                        lease_id = 0
                except grpc.RpcError:
                    lease_id = 0
            if not lease_id:
                lease_id = self._grant(ttl).ID
                with self._lock:
                    self._lease_cache[cache_key] = lease_id
        self._call(
            lambda ch: ETCD_KV.stub(ch).Put(
                rpc_pb2.PutRequest(
                    key=self._key(path), value=value.encode(), lease=lease_id
                ),
                timeout=self.timeout,
            )
        )

    # -- Lease helpers -----------------------------------------------------

    def _grant(self, ttl: float) -> rpc_pb2.LeaseGrantResponse:
        return self._call(
            lambda ch: ETCD_LEASE.stub(ch).LeaseGrant(
                rpc_pb2.LeaseGrantRequest(TTL=max(1, math.ceil(ttl))),
                timeout=self.timeout,
            )
        )

    def keepalive_once(self, lease_id: int) -> int:
        """One keep-alive round-trip; returns the remaining TTL (0 = the
        lease no longer exists).  Exposed for embedders that manage a
        long-lived lease themselves rather than re-storing."""

        def call(channel):
            replies = ETCD_LEASE.stub(channel).LeaseKeepAlive(
                iter([rpc_pb2.LeaseKeepAliveRequest(ID=lease_id)]),
                timeout=self.timeout,
            )
            for reply in replies:
                return reply.TTL
            return 0

        return self._call(call)

    # -- Watch -------------------------------------------------------------

    def watch(self, prefix: str, callback: WatchCallback) -> Callable[[], None]:
        """Watch ``prefix`` via an etcd Watch stream on a background
        thread.  The stream re-opens after transient failures until
        cancelled; events are re-filtered on path-segment boundaries like
        ``items``."""
        stop = threading.Event()
        ready = threading.Event()  # set at the create confirmation
        ns = len(self.namespace)
        start = self._key(prefix) if prefix else self.namespace.encode()

        state: dict = {"call": None}

        # Last-known state under the prefix, maintained by the watch
        # thread: the reopen RESYNC diffs a fresh Range against it and
        # synthesizes the PUT/DELETE events the outage swallowed.
        # Without this, a deregistration during an etcd blip would be
        # lost forever — the stream comes back healthy, so no
        # subscriber-side reconcile would ever fire again.
        known: dict[str, str] = {}
        seeded = False

        def safe_callback(path: str, value: str) -> None:
            try:
                callback(path, value)
            except Exception as exc:
                # A broken subscriber must not kill the watch for
                # every future event.
                log.current().error(
                    "watch callback failed", path=path, error=str(exc)
                )

        def resync() -> None:
            nonlocal seeded
            snapshot = dict(self.items(prefix))
            if not seeded:
                # First open: subscribers take their own initial
                # snapshot (e.g. WatchValues send_initial); just seed.
                known.update(snapshot)
                seeded = True
                return
            for path in list(known):
                if path not in snapshot:
                    known.pop(path)
                    safe_callback(path, "")
            for path, value in snapshot.items():
                if known.get(path) != value:
                    known[path] = value
                    safe_callback(path, value)

        def deliver(reply) -> None:
            for event in reply.events:
                try:
                    path = event.kv.key.decode()[ns:]
                except UnicodeDecodeError:
                    continue  # foreign binary key in the namespace
                if not _prefix_match(path, prefix):
                    continue
                deleted = event.type == rpc_pb2.Event.DELETE
                value = "" if deleted else event.kv.value.decode()
                if deleted:
                    known.pop(path, None)
                else:
                    known[path] = value
                safe_callback(path, value)

        def run() -> None:
            # Exponential reopen backoff, reset on any received reply;
            # only the FIRST failure after a healthy stream logs at
            # warning (an etcd outage must not flood the log at the
            # retry cadence).  The loop survives ANY exception — the
            # never-die heartbeat stance; only cancel/close end it.
            backoff, healthy = 0.5, True
            while not stop.is_set():
                try:
                    stub = ETCD_WATCH.stub(self._channel_get())
                    create = rpc_pb2.WatchRequest(
                        create_request=rpc_pb2.WatchCreateRequest(
                            key=start, range_end=_successor(start)
                        )
                    )
                    call = stub.Watch(iter([create]))
                    state["call"] = call
                    synced = False
                    for reply in call:
                        backoff, healthy = 0.5, True
                        if not synced:
                            # The create confirmation arrived: the
                            # stream is live, so a Range here + the
                            # events after it misses nothing.
                            resync()
                            synced = True
                        ready.set()
                        deliver(reply)
                    # Clean end-of-stream (server shutdown): back off
                    # before reopening, same as the error path.
                    stop.wait(backoff)
                except RuntimeError:
                    return  # db closed
                except Exception as exc:
                    is_rpc = isinstance(exc, grpc.RpcError)
                    if stop.is_set() or (
                        is_rpc and exc.code() == grpc.StatusCode.CANCELLED
                    ):
                        return
                    logger = (
                        log.current().warning if healthy else log.current().debug
                    )
                    logger(
                        "etcd watch interrupted; reopening",
                        endpoint=self.endpoint,
                        error=exc.code().name if is_rpc else repr(exc),
                        retry_in=backoff,
                    )
                    healthy = False
                    if is_rpc:
                        self._reset()
                    stop.wait(backoff)
                    backoff = min(backoff * 2, 15.0)

        thread = threading.Thread(
            target=run, daemon=True, name=f"etcd-watch-{prefix or '*'}"
        )
        thread.start()
        # Don't return until the watch is live (the create confirmation
        # arrived): a caller that stores immediately after watch() must
        # see the event.  Bounded — an unreachable etcd degrades to the
        # reopen loop rather than blocking the caller forever.
        ready.wait(timeout=self.timeout)

        def cancel() -> None:
            with self._lock:
                self._watch_cancels.discard(cancel)
            stop.set()
            call = state.get("call")
            if call is not None:
                call.cancel()
            thread.join(timeout=5)

        with self._lock:
            self._watch_cancels.add(cancel)
        return cancel

    def lookup(self, path: str) -> str:
        reply = self._call(
            lambda ch: ETCD_KV.stub(ch).Range(
                rpc_pb2.RangeRequest(key=self._key(path)), timeout=self.timeout
            )
        )
        return reply.kvs[0].value.decode() if reply.kvs else ""

    def items(self, prefix: str) -> list[tuple[str, str]]:
        start = self._key(prefix) if prefix else self.namespace.encode()
        reply = self._call(
            lambda ch: ETCD_KV.stub(ch).Range(
                rpc_pb2.RangeRequest(
                    key=start,
                    range_end=_successor(start),
                    sort_order=rpc_pb2.RangeRequest.ASCEND,
                    sort_target=rpc_pb2.RangeRequest.KEY,
                ),
                timeout=self.timeout,
            )
        )
        out = []
        ns = len(self.namespace)
        for kv in reply.kvs:
            path = kv.key.decode()[ns:]
            # Byte-prefix over-matches (foo matches foo-bar); keep only
            # path-segment matches, same rule as the other backends.
            if _prefix_match(path, prefix):
                out.append((path, kv.value.decode()))
        return out

    def keys(self, prefix: str) -> list[str]:
        return [k for k, _ in self.items(prefix)]

    def close(self) -> None:
        with self._lock:
            self._closed = True
            cancels = list(self._watch_cancels)
            self._watch_cancels.clear()
        for cancel in cancels:  # ends the watch threads for real —
            cancel()  # a closed DB must not keep redialing etcd
        self._reset()


def _range_contains(key: bytes, start: bytes, range_end: bytes) -> bool:
    """etcd range membership: no range_end = exact key; "\\0" = all keys
    >= start; otherwise [start, range_end)."""
    if not range_end:
        return key == start
    if range_end == b"\0":
        return key >= start
    return start <= key < range_end


class _WatchSession:
    """One Watch RPC: its outbound queue plus the watches multiplexed on
    it (watch_id → (key, range_end))."""

    def __init__(self) -> None:
        import queue

        self.queue: "queue.Queue[rpc_pb2.WatchResponse | None]" = queue.Queue()
        self.watches: dict[int, tuple[bytes, bytes]] = {}
        self.lock = threading.Lock()
        self.next_id = 1


class EtcdKVServer:
    """etcdserverpb KV/Watch/Lease servicer over a local RegistryDB store.

    The test double for EtcdRegistryDB — and, served from
    ``registry_main --etcd-listen``, a single-binary stand-in where a real
    etcd cluster is overkill.  Implements the Range/Put/DeleteRange subset
    with a monotonically increasing revision, Watch (create/cancel
    multiplexing, PUT/DELETE events), and Lease (grant/revoke/keepalive
    with real expiry: an expired lease deletes its attached keys and
    emits DELETE events) — enough for any client using etcd as a plain
    KV with liveness, which is exactly what EtcdRegistryDB is.
    """

    def __init__(self, db: RegistryDB | None = None) -> None:
        self.db = db if db is not None else MemRegistryDB()
        self._revision = 1
        self._lock = threading.Lock()
        self._sessions: set[_WatchSession] = set()
        self._sessions_lock = threading.Lock()
        self._event_q: list[tuple[str, str, bool, int]] = []
        self._event_lock = threading.Lock()
        self._ev_draining = False
        # Lease state: id → attached keys; key → owning lease.  The
        # sweeper expires by stringified lease id.
        self._leases: dict[int, set[str]] = {}
        self._lease_ttl: dict[int, int] = {}
        self._key_lease: dict[str, int] = {}
        self._next_lease = int(time.time()) << 16
        self._lease_sweeper = _LeaseSweeper(self._expire_lease)

    def _header(self) -> rpc_pb2.ResponseHeader:
        with self._lock:
            return rpc_pb2.ResponseHeader(revision=self._revision)

    # -- Watch fan-out -----------------------------------------------------
    #
    # Ordering contract: mutators append to _event_q while HOLDING
    # self._lock (queue order = revision order) and call
    # _dispatch_events after releasing it; one drainer at a time fans
    # out to sessions, so two racing mutations of one key can never
    # reach a watcher reversed (the _EventHub discipline, server-side).

    def _enqueue_event(self, key: str, value: str, deleted: bool) -> None:
        """Call while holding self._lock (after the revision bump) —
        that is what makes queue order equal revision order."""
        with self._event_lock:
            self._event_q.append((key, value, deleted, self._revision))

    def _dispatch_events(self) -> None:
        while True:
            with self._event_lock:
                if self._ev_draining or not self._event_q:
                    return
                self._ev_draining = True
            try:
                while True:
                    with self._event_lock:
                        if not self._event_q:
                            break
                        key, value, deleted, revision = self._event_q.pop(0)
                    self._fan_out(key, value, deleted, revision)
            finally:
                with self._event_lock:
                    self._ev_draining = False

    def _fan_out(
        self, key: str, value: str, deleted: bool, revision: int
    ) -> None:
        kb = key.encode()
        with self._sessions_lock:
            sessions = list(self._sessions)
        for session in sessions:
            with session.lock:
                matched = [
                    wid
                    for wid, (start, range_end) in session.watches.items()
                    if _range_contains(kb, start, range_end)
                ]
            for wid in matched:
                event = rpc_pb2.Event(
                    type=(
                        rpc_pb2.Event.DELETE if deleted else rpc_pb2.Event.PUT
                    ),
                    kv=rpc_pb2.KeyValue(
                        key=kb,
                        value=b"" if deleted else value.encode(),
                        mod_revision=revision,
                    ),
                )
                session.queue.put(
                    rpc_pb2.WatchResponse(
                        header=rpc_pb2.ResponseHeader(revision=revision),
                        watch_id=wid,
                        events=[event],
                    )
                )

    # Stored keys are raw (namespace included); this server does not
    # interpret paths, exactly like etcd.

    def Range(self, request, context) -> rpc_pb2.RangeResponse:
        reply = rpc_pb2.RangeResponse(header=self._header())
        key = request.key.decode()
        if not request.range_end:
            value = self.db.lookup(key)
            if value:
                reply.kvs.add(key=request.key, value=value.encode())
        else:
            # db.items("") is every key; range-filter client-side with
            # the same membership rule watches use.  The in-process
            # store is small by construction.
            for path, value in self.db.items(""):
                if _range_contains(
                    path.encode(), request.key, request.range_end
                ):
                    reply.kvs.add(key=path.encode(), value=value.encode())
            if request.sort_order == rpc_pb2.RangeRequest.DESCEND:
                reversed_kvs = list(reversed(reply.kvs))
                del reply.kvs[:]
                for kv in reversed_kvs:
                    reply.kvs.add().CopyFrom(kv)
        reply.count = len(reply.kvs)
        if request.count_only:
            del reply.kvs[:]
        return reply

    def Put(self, request, context) -> rpc_pb2.PutResponse:
        if not request.key:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "key required")
        key = request.key.decode()
        value = request.value.decode()
        # Lease check, store, and attach are ONE critical section: a
        # lease expiring mid-Put either beats the check (NOT_FOUND, the
        # heartbeat retries with a fresh lease) or waits for the whole
        # Put and then deletes the attached key — never a key stored
        # persistent because its lease vanished between two lock takes.
        with self._lock:
            if request.lease and request.lease not in self._leases:
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    "etcdserverpb: requested lease not found",
                )
            self.db.store(key, value)
            # Re-attaching a key moves it between leases (etcd semantics:
            # a key belongs to the lease of its LAST put; a put without a
            # lease makes it persistent).
            old = self._key_lease.pop(key, None)
            if old is not None and old in self._leases:
                self._leases[old].discard(key)
            if request.lease:
                self._leases[request.lease].add(key)
                self._key_lease[key] = request.lease
            self._revision += 1
            self._enqueue_event(key, value, deleted=False)
        self._dispatch_events()
        return rpc_pb2.PutResponse(header=self._header())

    def _delete_locked(self, key: str) -> bool:
        """Delete + detach under self._lock; caller notifies after
        releasing it (``_notify`` re-takes the lock for the header)."""
        if not self.db.lookup(key):
            return False
        self.db.store(key, "")
        lease = self._key_lease.pop(key, None)
        if lease is not None and lease in self._leases:
            self._leases[lease].discard(key)
        return True

    def DeleteRange(self, request, context) -> rpc_pb2.DeleteRangeResponse:
        key = request.key.decode()
        deleted: list[str] = []
        with self._lock:
            if not request.range_end:
                candidates = [key]
            else:
                candidates = [
                    path
                    for path, _ in self.db.items("")
                    if _range_contains(
                        path.encode(), request.key, request.range_end
                    )
                ]
            for path in candidates:
                if self._delete_locked(path):
                    deleted.append(path)
            if deleted:
                self._revision += 1
            for path in deleted:
                self._enqueue_event(path, "", deleted=True)
        self._dispatch_events()
        return rpc_pb2.DeleteRangeResponse(
            header=self._header(), deleted=len(deleted)
        )

    # -- Watch service -----------------------------------------------------

    def Watch(self, request_iterator, context) -> Iterator[rpc_pb2.WatchResponse]:
        session = _WatchSession()
        with self._sessions_lock:
            self._sessions.add(session)

        def read_requests() -> None:
            try:
                for request in request_iterator:
                    which = request.WhichOneof("request_union")
                    if which == "create_request":
                        create = request.create_request
                        with session.lock:
                            wid = create.watch_id or session.next_id
                            session.next_id = max(session.next_id, wid) + 1
                            session.watches[wid] = (
                                bytes(create.key),
                                bytes(create.range_end),
                            )
                        session.queue.put(
                            rpc_pb2.WatchResponse(
                                header=self._header(),
                                watch_id=wid,
                                created=True,
                            )
                        )
                    elif which == "cancel_request":
                        wid = request.cancel_request.watch_id
                        with session.lock:
                            session.watches.pop(wid, None)
                        session.queue.put(
                            rpc_pb2.WatchResponse(
                                header=self._header(),
                                watch_id=wid,
                                canceled=True,
                            )
                        )
            except Exception:
                pass  # client hung up mid-read; the RPC callback ends us
            # NOTE: request-stream exhaustion (client half-close) does NOT
            # end the watch — events keep flowing until the RPC terminates,
            # matching etcd.

        reader = threading.Thread(target=read_requests, daemon=True)
        reader.start()
        # End the response loop when the RPC terminates (client cancel,
        # disconnect, server shutdown).  add_callback returns False when
        # the RPC already terminated — the callback will never fire, so
        # enqueue the sentinel ourselves or the worker blocks forever.
        if not context.add_callback(lambda: session.queue.put(None)):
            session.queue.put(None)
        try:
            while True:
                response = session.queue.get()
                if response is None:
                    return
                yield response
        finally:
            with self._sessions_lock:
                self._sessions.discard(session)

    # -- Lease service -----------------------------------------------------

    def LeaseGrant(self, request, context) -> rpc_pb2.LeaseGrantResponse:
        ttl = max(1, int(request.TTL))
        with self._lock:
            lease_id = request.ID or self._next_lease
            self._next_lease = max(self._next_lease, lease_id) + 1
            if request.ID and request.ID in self._leases:
                # Response built OUTSIDE the critical section: _header()
                # takes self._lock itself (non-reentrant), so calling it
                # here would self-deadlock on the duplicate-grant path.
                duplicate = True
            else:
                duplicate = False
                self._leases[lease_id] = set()
                self._lease_ttl[lease_id] = ttl
                self._lease_sweeper.arm(str(lease_id), time.monotonic() + ttl)
        if duplicate:
            return rpc_pb2.LeaseGrantResponse(
                header=self._header(),
                error="lease already exists",
            )
        return rpc_pb2.LeaseGrantResponse(
            header=self._header(), ID=lease_id, TTL=ttl
        )

    def _expire_lease(self, lease_id_str: str, seq: int) -> None:
        self._revoke(int(lease_id_str), seq=seq)

    def _revoke(self, lease_id: int, seq: int | None = None) -> bool:
        """Revoke + delete attached keys atomically (one critical
        section, like etcd's raft-applied revoke).  ``seq`` set = expiry
        path: a keep-alive that re-armed since this deadline fired wins
        (``still_current`` checked under the same lock the keep-alive
        arms under)."""
        deleted: list[str] = []
        with self._lock:
            if seq is not None and not self._lease_sweeper.still_current(
                str(lease_id), seq
            ):
                return False
            keys = self._leases.pop(lease_id, None)
            self._lease_ttl.pop(lease_id, None)
            self._lease_sweeper.disarm(str(lease_id))
            if keys is None:
                return False
            for key in keys:
                # Only keys still attached to THIS lease die with it.
                if self._key_lease.get(key) == lease_id:
                    self._key_lease.pop(key, None)
                    if self.db.lookup(key):
                        self.db.store(key, "")
                        deleted.append(key)
            if deleted:
                self._revision += 1
            for key in deleted:
                self._enqueue_event(key, "", deleted=True)
        self._dispatch_events()
        return True

    def LeaseRevoke(self, request, context) -> rpc_pb2.LeaseRevokeResponse:
        if not self._revoke(request.ID):
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                "etcdserverpb: requested lease not found",
            )
        return rpc_pb2.LeaseRevokeResponse(header=self._header())

    def LeaseKeepAlive(
        self, request_iterator, context
    ) -> Iterator[rpc_pb2.LeaseKeepAliveResponse]:
        for request in request_iterator:
            with self._lock:
                known = request.ID in self._leases
                ttl = self._lease_ttl.get(request.ID, 0)
                if known:
                    self._lease_sweeper.arm(
                        str(request.ID), time.monotonic() + ttl
                    )
            yield rpc_pb2.LeaseKeepAliveResponse(
                header=self._header(),
                ID=request.ID,
                TTL=ttl if known else 0,
            )

    def close(self) -> None:
        self._lease_sweeper.close()

    def start_server(self, endpoint: str, tls=None, max_workers: int = 64):
        from oim_tpu.common.server import NonBlockingGRPCServer

        # Each Watch RPC pins a worker for its lifetime (sync gRPC), so
        # the pool must dwarf the expected watcher count or watchers
        # starve Put/Range — including the heartbeats whose leases then
        # expire fleet-wide.  Same sizing rationale as
        # Registry.start_server.
        srv = NonBlockingGRPCServer(endpoint, tls=tls, max_workers=max_workers)

        def register(server):
            ETCD_KV.registrar(self)(server)
            ETCD_WATCH.registrar(self)(server)
            ETCD_LEASE.registrar(self)(server)

        srv.start(register)
        return srv
