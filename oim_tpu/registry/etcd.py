"""Etcd-backed RegistryDB + an in-process etcd-compatible KV server.

Fills the seam the reference reserved for etcd but never implemented
(reference pkg/oim-registry/registry.go:31-41 — "behind the RegistryDB
interface"; README.md:131-135).  ``EtcdRegistryDB`` is a client of the
etcd v3 KV gRPC API (proto/etcd/rpc.proto, the Range/Put/DeleteRange
subset), so a production registry can point at a real etcd cluster for
replicated durable state (BASELINE.json config 5: N controllers behind an
etcd-backed registry).  ``EtcdKVServer`` serves the same wire subset from
a local ``RegistryDB`` — the test double, and a single-binary option.

Registry paths map to etcd keys as ``<namespace><path>`` (default
namespace ``/oim/``).  Prefix queries use etcd's range convention
[key, successor(key)) and re-filter on path-segment boundaries, since a
byte prefix also matches sibling keys like ``foo-bar`` for prefix ``foo``.
"""

from __future__ import annotations

import threading
from typing import Callable

import grpc

from oim_tpu import log
from oim_tpu.registry.db import MemRegistryDB, RegistryDB, _prefix_match
from oim_tpu.spec.gen.etcd import rpc_pb2
from oim_tpu.spec.rpc import ServiceSpec

ETCD_KV = ServiceSpec(
    "etcdserverpb.KV",
    {
        "Range": (rpc_pb2.RangeRequest, rpc_pb2.RangeResponse),
        "Put": (rpc_pb2.PutRequest, rpc_pb2.PutResponse),
        "DeleteRange": (rpc_pb2.DeleteRangeRequest, rpc_pb2.DeleteRangeResponse),
    },
)

DEFAULT_NAMESPACE = "/oim/"


def _successor(key: bytes) -> bytes:
    """etcd prefix range end: the key with its last byte incremented
    (keys are namespace-prefixed and non-empty, and the namespace contains
    no 0xff bytes, so no carry handling is needed)."""
    return key[:-1] + bytes([key[-1] + 1])


class EtcdRegistryDB:
    """RegistryDB speaking the etcd v3 KV API.

    One persistent channel (etcd client convention), with a single
    reconnect retry per call so a restarted etcd member doesn't require a
    registry restart — the same per-operation resilience stance as the
    rest of the control plane.
    """

    def __init__(
        self,
        endpoint: str,
        namespace: str = DEFAULT_NAMESPACE,
        credentials: grpc.ChannelCredentials | None = None,
        timeout: float = 10.0,
        channel_factory: Callable[[], grpc.Channel] | None = None,
    ) -> None:
        self.endpoint = endpoint
        self.namespace = namespace
        self.timeout = timeout
        self._credentials = credentials
        self._channel_factory = channel_factory or self._dial
        self._lock = threading.Lock()
        self._channel: grpc.Channel | None = None

    def _dial(self) -> grpc.Channel:
        from oim_tpu.common import endpoint as ep

        target = ep.parse(self.endpoint).grpc_target()
        if self._credentials is not None:
            return grpc.secure_channel(target, self._credentials)
        return grpc.insecure_channel(target)

    def _stub(self):
        with self._lock:
            if self._channel is None:
                self._channel = self._channel_factory()
            return ETCD_KV.stub(self._channel)

    def _reset(self) -> None:
        with self._lock:
            if self._channel is not None:
                try:
                    self._channel.close()
                except Exception:
                    pass
                self._channel = None

    def _call(self, fn):
        try:
            return fn(self._stub())
        except grpc.RpcError as exc:
            if exc.code() != grpc.StatusCode.UNAVAILABLE:
                raise
            log.current().warning(
                "etcd unavailable; redialing", endpoint=self.endpoint
            )
            self._reset()
            return fn(self._stub())

    def _key(self, path: str) -> bytes:
        return (self.namespace + path).encode()

    # -- RegistryDB --------------------------------------------------------

    def store(self, path: str, value: str) -> None:
        if value == "":
            self._call(
                lambda s: s.DeleteRange(
                    rpc_pb2.DeleteRangeRequest(key=self._key(path)),
                    timeout=self.timeout,
                )
            )
        else:
            self._call(
                lambda s: s.Put(
                    rpc_pb2.PutRequest(key=self._key(path), value=value.encode()),
                    timeout=self.timeout,
                )
            )

    def lookup(self, path: str) -> str:
        reply = self._call(
            lambda s: s.Range(
                rpc_pb2.RangeRequest(key=self._key(path)), timeout=self.timeout
            )
        )
        return reply.kvs[0].value.decode() if reply.kvs else ""

    def items(self, prefix: str) -> list[tuple[str, str]]:
        start = self._key(prefix) if prefix else self.namespace.encode()
        reply = self._call(
            lambda s: s.Range(
                rpc_pb2.RangeRequest(
                    key=start,
                    range_end=_successor(start),
                    sort_order=rpc_pb2.RangeRequest.ASCEND,
                    sort_target=rpc_pb2.RangeRequest.KEY,
                ),
                timeout=self.timeout,
            )
        )
        out = []
        ns = len(self.namespace)
        for kv in reply.kvs:
            path = kv.key.decode()[ns:]
            # Byte-prefix over-matches (foo matches foo-bar); keep only
            # path-segment matches, same rule as the other backends.
            if _prefix_match(path, prefix):
                out.append((path, kv.value.decode()))
        return out

    def keys(self, prefix: str) -> list[str]:
        return [k for k, _ in self.items(prefix)]

    def close(self) -> None:
        self._reset()


class EtcdKVServer:
    """etcdserverpb.KV servicer over a local RegistryDB store.

    The test double for EtcdRegistryDB — and, served from
    ``registry_main --etcd-listen``, a single-binary stand-in where a real
    etcd cluster is overkill.  Implements the Range/Put/DeleteRange subset
    with a monotonically increasing revision, enough for any client using
    etcd as a plain KV (prefix ranges, single-key gets, deletes).
    """

    def __init__(self, db: RegistryDB | None = None) -> None:
        self.db = db if db is not None else MemRegistryDB()
        self._revision = 1
        self._lock = threading.Lock()

    def _bump(self) -> int:
        with self._lock:
            self._revision += 1
            return self._revision

    def _header(self) -> rpc_pb2.ResponseHeader:
        with self._lock:
            return rpc_pb2.ResponseHeader(revision=self._revision)

    # Stored keys are raw (namespace included); this server does not
    # interpret paths, exactly like etcd.

    def Range(self, request, context) -> rpc_pb2.RangeResponse:
        reply = rpc_pb2.RangeResponse(header=self._header())
        key = request.key.decode()
        if not request.range_end:
            value = self.db.lookup(key)
            if value:
                reply.kvs.add(key=request.key, value=value.encode())
        else:
            end = request.range_end.decode()
            # db.items("") is every key; range-filter client-side.  The
            # in-process store is small by construction.
            for path, value in self.db.items(""):
                if key <= path < end or request.range_end == b"\0":
                    reply.kvs.add(key=path.encode(), value=value.encode())
            if request.sort_order == rpc_pb2.RangeRequest.DESCEND:
                reversed_kvs = list(reversed(reply.kvs))
                del reply.kvs[:]
                for kv in reversed_kvs:
                    reply.kvs.add().CopyFrom(kv)
        reply.count = len(reply.kvs)
        if request.count_only:
            del reply.kvs[:]
        return reply

    def Put(self, request, context) -> rpc_pb2.PutResponse:
        if not request.key:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "key required")
        self.db.store(request.key.decode(), request.value.decode())
        self._bump()
        return rpc_pb2.PutResponse(header=self._header())

    def DeleteRange(self, request, context) -> rpc_pb2.DeleteRangeResponse:
        key = request.key.decode()
        deleted = 0
        if not request.range_end:
            if self.db.lookup(key):
                self.db.store(key, "")
                deleted = 1
        else:
            end = request.range_end.decode()
            for path, _ in self.db.items(""):
                if key <= path < end or request.range_end == b"\0":
                    self.db.store(path, "")
                    deleted += 1
        if deleted:
            self._bump()
        return rpc_pb2.DeleteRangeResponse(header=self._header(), deleted=deleted)

    def start_server(self, endpoint: str, tls=None):
        from oim_tpu.common.server import NonBlockingGRPCServer

        srv = NonBlockingGRPCServer(endpoint, tls=tls)
        srv.start(ETCD_KV.registrar(self))
        return srv
