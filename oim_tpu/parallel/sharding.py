"""Logical-dimension → mesh-axis sharding rules.

Arrays are annotated with *logical* dimension names ("batch", "seq",
"heads", ...); ``ShardingRules`` maps those to mesh axes, so a model written
once runs under any parallelism mix — change the rules, not the model.  XLA
(GSPMD) inserts the collectives implied by the shardings; the framework only
drops explicit `shard_map` down where the schedule itself matters (ring
attention, pipeline).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclass(frozen=True)
class ShardingRules:
    batch: str | None = "dp"
    seq: str | None = "sp"
    heads: str | None = "tp"
    model: str | None = None  # d_model stays replicated by default
    mlp: str | None = "tp"  # ffn hidden
    vocab: str | None = "tp"
    experts: str | None = "ep"
    stages: str | None = "pp"  # stacked pipeline stage dimension

    def axis_for(self, logical: str | None) -> str | None:
        if logical is None:
            return None
        try:
            return getattr(self, logical)
        except AttributeError:
            raise ValueError(f"unknown logical dimension {logical!r}") from None


DEFAULT_RULES = ShardingRules()


def partition_spec(
    logical_dims: tuple[str | None, ...], rules: ShardingRules = DEFAULT_RULES
) -> PartitionSpec:
    return PartitionSpec(*(rules.axis_for(dim) for dim in logical_dims))


def named_sharding(
    mesh: Mesh,
    logical_dims: tuple[str | None, ...],
    rules: ShardingRules = DEFAULT_RULES,
) -> NamedSharding:
    return NamedSharding(mesh, partition_spec(logical_dims, rules))


def constrain(
    x,
    logical_dims: tuple[str | None, ...],
    rules: ShardingRules = DEFAULT_RULES,
):
    """``with_sharding_constraint`` by logical names; under jit with a mesh
    in scope this pins activation layouts so GSPMD keeps collectives where
    intended (HBM-bandwidth control)."""
    return jax.lax.with_sharding_constraint(x, partition_spec(logical_dims, rules))


def shard_pytree(params, mesh: Mesh, logical_tree, rules: ShardingRules = DEFAULT_RULES):
    """Place a parameter pytree onto the mesh.

    ``logical_tree`` mirrors ``params`` with tuples of logical dim names as
    leaves.  Uses ``jax.device_put`` which is a no-op for already-correct
    placements.
    """
    return jax.tree.map(
        lambda x, logical: jax.device_put(x, named_sharding(mesh, logical, rules)),
        params,
        logical_tree,
    )
