"""Ulysses sequence parallelism: all-to-all seq↔head resharding.

The second sequence-parallel scheme of the framework (SURVEY.md §2.3),
complementing ring attention: instead of rotating K/V blocks around a ring,
each device trades its *sequence* shard for a *head* shard with one
``all_to_all``, computes ordinary full-sequence attention on its heads, and
trades back (DeepSpeed-Ulysses style, implemented from scratch for this
framework).

Trade-offs vs the ring (why both exist):

- Ulysses moves Q, K, V, O once each (4 tensor-sized all-to-alls total);
  the ring moves K and V ``sp`` times (2·sp neighbor hops).  For short-to-
  moderate sequences or fat heads the all-to-all wins; for very long
  sequences the ring wins on memory — Ulysses materializes the full
  sequence per device (heads sharded) during the local attention, and with
  ``use_flash`` the current flash kernel additionally holds one head's full
  global K/V in VMEM per grid step and recomputes the backward through the
  O(T²) reference formula.  Long-context *training* should therefore use
  the ring; Ulysses shines for inference/prefill and moderate-T training.
- Ulysses needs ``heads % sp == 0``; the ring has no such constraint.
- On a TPU torus, ``all_to_all`` over a mesh axis is an XLA collective that
  rides ICI links directly.

Both schemes consume the same layout — ``[batch, seq_local, heads, head_dim]``
sharded on ``sp`` — so the model layer can switch per-config
(``TransformerConfig.attn_impl``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from oim_tpu.ops.flash_attention import flash_attention, reference_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    use_flash: bool = True,
    segments: jax.Array | None = None,
    window: int = 0,
) -> jax.Array:
    """Exact attention over sequence shards via all-to-all resharding.

    Args:
      q, k, v: local shards ``[batch, seq_local, heads, head_dim]``; the
        global sequence is the concatenation over ``axis_name`` in
        axis-index order (same contract as ``ring_attention``).
      axis_name: mesh axis carrying the sequence shards (``sp``).
      causal: causal masking in global positions.
      use_flash: run the local attention through the pallas flash kernel
        (falls back to the reference path off-TPU / for ragged shapes).
      segments: local ``[batch, seq_local]`` segment ids (sequence
        packing) — all-gathered over ``axis_name`` since after the
        all-to-all every device attends over the FULL sequence.

    Returns the local output shard ``[batch, seq_local, heads, head_dim]``.
    """
    size = jax.lax.axis_size(axis_name)
    if size == 1:
        if use_flash:
            return flash_attention(
                q, k, v, causal, window=window, segments=segments
            )
        return reference_attention(q, k, v, causal, segments, window)
    heads = q.shape[2]
    if heads % size != 0:
        raise ValueError(
            f"ulysses needs heads % sp == 0, got {heads} heads over "
            f"sp={size} (use ring attention for this shape)"
        )

    # Trade sequence shards for head shards: [B, T/sp, H, D] → [B, T, H/sp, D].
    # tiled all_to_all splits the head axis into sp chunks and concatenates
    # the gathered sequence blocks in axis-index order, which preserves
    # global positions exactly because the sp axis order IS the sequence
    # order (mesh contract above).
    def seq_to_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    q_full = seq_to_heads(q)
    k_full = seq_to_heads(k)
    v_full = seq_to_heads(v)
    seg_full = (
        None if segments is None
        else jax.lax.all_gather(
            segments.astype(jnp.int32), axis_name, axis=1, tiled=True
        )
    )

    if use_flash:
        o_full = flash_attention(
            q_full, k_full, v_full, causal, window=window,
            segments=seg_full,
        )
    else:
        o_full = reference_attention(
            q_full, k_full, v_full, causal, seg_full, window
        )

    return heads_to_seq(o_full)


def ulysses_attention_sharded(
    q, k, v, mesh, causal: bool = True, segments=None, window: int = 0
):
    """Convenience wrapper: global arrays in, global arrays out, sequence
    sharded over ``sp`` and batch over ``dp`` (mirror of
    ``ring_attention_sharded``; ``segments`` [B, T] shards the same way)."""
    from jax.sharding import PartitionSpec as P

    spec = P("dp", "sp", None, None)
    if segments is None:
        fn = jax.shard_map(
            partial(ulysses_attention, axis_name="sp", causal=causal,
                    window=window),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        return fn(q, k, v)
    fn = jax.shard_map(
        lambda q_, k_, v_, s_: ulysses_attention(
            q_, k_, v_, "sp", causal=causal, segments=s_, window=window
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, P("dp", "sp")),
        out_specs=spec,
    )
    return fn(q, k, v, segments)
