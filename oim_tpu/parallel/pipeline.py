"""Pipeline parallelism: GPipe-style microbatch schedule on the ``pp`` axis.

Written for per-device SPMD code (inside ``shard_map``): each pipeline stage
holds its slice of the layer stack; activations hop stage→stage with
``ppermute`` while microbatches stream through, so at steady state every
stage computes every step.  The backward pass falls out of JAX's transpose
of the scan+ppermute (reverse schedule).

Memory: with ``stage_remat`` (default) each schedule step stores only its
stage *input* for the backward and recomputes the stage's layers — peak
activation memory drops from O(steps · layers_per_stage) to O(steps)
activations per device.

``pipeline_1f1b_value_and_grad`` goes further: a hand-interleaved 1F1B
schedule cannot be expressed through plain autodiff (JAX runs the whole
forward, then the transposed backward — GPipe order by construction), so
it OWNS its backward: each tick runs one microbatch-forward and one
microbatch-backward (explicit ``jax.vjp`` recomputed from the stored stage
*input*), activation cotangents ppermute stage→stage-1 while activations
ppermute stage→stage+1, the per-microbatch loss is computed on the last
stage inside the schedule, and parameter gradients accumulate in the
carry.  In-flight stage inputs are bounded by ``min(M, 2·S-1)`` instead of
``M + S - 1``, and the loss head sees one microbatch at a time (no [M]
output buffer, no full-batch logits).

The GPipe schedule runs ``n_micro + n_stages - 1`` steps; device ``i``
works on microbatch ``step - i`` when that index is valid.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def gpipe_spmd(
    stage_fn: Callable,
    stage_params,
    x_microbatches: jax.Array,
    axis_name: str = "pp",
    stage_remat: bool = True,
):
    """Run the pipeline inside shard_map.

    Args:
      stage_fn: ``(stage_params, activation, mb_idx) -> (activation,
        aux)`` for one stage's layer stack; activation shape ``[mb, ...]``
        must be preserved, ``aux`` is a scalar auxiliary loss (e.g. MoE
        load balancing) summed over the stage's layers.  ``mb_idx`` is
        the (clipped) index of the microbatch being processed — consumed
        by microbatch-dependent closures (packed segment ids); bubble
        steps pass a clipped index and their output is masked anyway.
      stage_params: THIS stage's parameters (already sliced by shard_map).
      x_microbatches: ``[n_micro, mb, ...]`` — the stage-0 input stream
        (replicated over ``pp``; only stage 0 reads it).
      axis_name: the pipeline mesh axis.
      stage_remat: rematerialize the stage in the backward instead of
        storing every layer's activations per schedule step.

    Returns ``(outputs, aux)``: outputs ``[n_micro, mb, ...]`` are REAL ONLY
    ON THE LAST STAGE (zeros elsewhere — the caller's loss must mask to the
    last stage, which also keeps replicated-param gradients single-sourced);
    ``aux`` is THIS stage's mean-over-microbatches auxiliary loss (bubble
    steps masked out) — the caller psums over the pipeline axis for the
    global value.  Kept per-stage deliberately: inside shard_map the
    transpose of psum re-sums cotangents across devices, so a psum
    buried in a differentiated loss inflates its gradients by the axis
    size (see models/train.py ``_local_objective``).
    """
    size = jax.lax.axis_size(axis_name)
    index = jax.lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    total_steps = n_micro + size - 1

    if stage_remat:
        stage_fn = jax.checkpoint(stage_fn)

    perm = [(i, (i + 1) % size) for i in range(size)]
    out_shape, _ = jax.eval_shape(
        lambda p, a: stage_fn(p, a, jnp.zeros((), jnp.int32)),
        stage_params, x_microbatches[0],
    )
    out_dtype = out_shape.dtype

    def step(carry, step_idx):
        state, outputs, aux_sum = carry
        # Activation arriving from the previous stage.
        received = jax.lax.ppermute(state, axis_name, perm)
        feed_idx = jnp.clip(step_idx, 0, n_micro - 1)
        stage0_in = jax.lax.dynamic_index_in_dim(
            x_microbatches, feed_idx, axis=0, keepdims=False
        ).astype(out_dtype)
        my_input = jnp.where(index == 0, stage0_in, received)
        mb_idx = step_idx - index
        state, aux = stage_fn(
            stage_params, my_input, jnp.clip(mb_idx, 0, n_micro - 1)
        )
        # Bubble steps compute on garbage; count aux only when this stage
        # holds a real microbatch (step - index ∈ [0, n_micro)).
        is_real = jnp.logical_and(mb_idx >= 0, mb_idx < n_micro)
        aux_sum = aux_sum + jnp.where(is_real, aux, 0.0)
        # The last stage emits microbatch (step - size + 1) when valid.
        out_idx = step_idx - (size - 1)
        is_valid = jnp.logical_and(index == size - 1, out_idx >= 0)
        write_idx = jnp.clip(out_idx, 0, n_micro - 1)
        current = jax.lax.dynamic_index_in_dim(
            outputs, write_idx, axis=0, keepdims=False
        )
        updated = jnp.where(is_valid, state, current)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, updated, write_idx, axis=0
        )
        return (state, outputs, aux_sum), None

    # The carry varies per pipeline stage; mark the zero inits accordingly
    # (shard_map VMA typing).
    state0 = jax.lax.pcast(
        jnp.zeros(mb_shape, dtype=out_dtype), (axis_name,), to="varying"
    )
    outputs0 = jax.lax.pcast(
        jnp.zeros((n_micro, *mb_shape), dtype=out_dtype),
        (axis_name,),
        to="varying",
    )
    aux0 = jax.lax.pcast(
        jnp.zeros((), jnp.float32), (axis_name,), to="varying"
    )
    (_, outputs, aux_sum), _ = jax.lax.scan(
        step, (state0, outputs0, aux0), jnp.arange(total_steps)
    )
    # Each stage saw every microbatch once; average over microbatches to
    # match the non-pp path (per-stage — the caller psums over ``pp``).
    return outputs, aux_sum / n_micro


def pipeline_1f1b_value_and_grad(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    head_params,
    x_microbatches: jax.Array,
    aux_seed: float = 0.0,
    axis_name: str = "pp",
):
    """Interleaved 1F1B: forward AND backward inside one lockstep schedule.

    Args:
      stage_fn: ``(stage_params, activation, mb_idx) -> (activation,
        aux)`` (same contract as ``gpipe_spmd``).
      loss_fn: ``(head_params, activation, mb_index) -> (loss, ce)`` —
        per-microbatch scalars, already weighted so that summing over
        microbatches (last stage) yields the global objective's local
        contribution.  Evaluated on every stage (SPMD lockstep) but only
        the last stage's value/cotangent count.
      stage_params: THIS stage's layer parameters.
      head_params: the loss head's parameters (final norm / unembedding);
        their gradient comes back nonzero only on the last stage.
      x_microbatches: ``[M, mb, ...]`` stage-0 input stream.
      aux_seed: cotangent for each (stage, microbatch) aux value — the
        caller's aux-loss weight divided by whatever normalization it
        applies across microbatches/devices.

    Returns ``(loss, ce, aux, d_stage_params, d_head_params,
    dx_microbatches)``: loss/ce are this device's summed contributions
    (real on the last stage, zeros elsewhere — psum over the mesh
    afterwards); aux is this stage's summed auxiliary loss over its real
    microbatches (psum over the axis, divide by M for the mean);
    dx_microbatches is real on stage 0 (the embedding cotangent).

    Schedule: one F half-tick and one B half-tick per iteration, B lagging
    F by S-1 ticks, for ``M + 2(S-1)`` iterations.  Stage ``i`` forwards
    microbatch ``k - i`` and backwards microbatch ``k - 2(S-1) + i`` at
    iteration ``k`` — the Megatron 1F1B timetable in SPMD lockstep form.
    Each stage holds at most ``min(M, 2S-1)`` in-flight stage inputs; the
    backward recomputes the stage (activation remat) from the stored
    input, so no per-layer residuals persist across ticks.
    """
    size = jax.lax.axis_size(axis_name)
    index = jax.lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    lag = 2 * (size - 1)
    total_ticks = n_micro + lag
    ring = min(n_micro, 2 * size - 1)  # max in-flight inputs per stage

    perm_fwd = [(i, (i + 1) % size) for i in range(size)]
    perm_bwd = [(i, (i - 1) % size) for i in range(size)]

    out_shape, _ = jax.eval_shape(
        lambda p, a: stage_fn(p, a, jnp.zeros((), jnp.int32)),
        stage_params, x_microbatches[0],
    )
    dtype = out_shape.dtype

    def tick(carry, k):
        (
            fwd_state, bwd_cot, acts, d_sp, d_hp, dx,
            loss_acc, ce_acc, aux_acc,
        ) = carry

        # ---- F half-tick: stage i forwards microbatch k - i.
        m_f = k - index
        f_valid = jnp.logical_and(m_f >= 0, m_f < n_micro)
        received = jax.lax.ppermute(fwd_state, axis_name, perm_fwd)
        feed = jax.lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(m_f, 0, n_micro - 1), 0, keepdims=False
        ).astype(dtype)
        my_input = jnp.where(index == 0, feed, received)
        slot_f = jnp.mod(m_f, ring)
        stale = jax.lax.dynamic_index_in_dim(acts, slot_f, 0, keepdims=False)
        acts = jax.lax.dynamic_update_index_in_dim(
            acts, jnp.where(f_valid, my_input, stale), slot_f, 0
        )
        y, _ = stage_fn(
            stage_params, my_input, jnp.clip(m_f, 0, n_micro - 1)
        )
        fwd_state = y

        # ---- B half-tick: stage i backwards microbatch k - 2(S-1) + i.
        m_b = k - lag + index
        b_valid = jnp.logical_and(m_b >= 0, m_b < n_micro)
        received_cot = jax.lax.ppermute(bwd_cot, axis_name, perm_bwd)
        slot_b = jnp.mod(m_b, ring)
        act_in = jax.lax.dynamic_index_in_dim(acts, slot_b, 0, keepdims=False)
        mb_index = jnp.clip(m_b, 0, n_micro - 1)

        def full(sp, hp, act):
            y, aux = stage_fn(sp, act, mb_index)
            loss, ce = loss_fn(hp, y, mb_index)
            return y, aux, loss, ce

        (y_b, _aux_b, loss_b, ce_b), vjp = jax.vjp(
            full, stage_params, head_params, act_in
        )
        is_last = index == size - 1
        # Seeds: activation cotangent from the next stage (zero on the
        # last stage, whose output only feeds the loss), the aux weight,
        # the loss itself on the last stage, ce never (metric only).
        dy = jnp.where(is_last, jnp.zeros_like(received_cot), received_cot)
        seed_loss = jnp.where(is_last, 1.0, 0.0).astype(loss_b.dtype)
        g_sp, g_hp, g_act = vjp(
            (dy, jnp.asarray(aux_seed, _aux_b.dtype), seed_loss,
             jnp.zeros_like(ce_b))
        )
        keep = lambda new, old: jax.tree_util.tree_map(  # noqa: E731
            lambda n, o: jnp.where(b_valid, o + n, o), new, old
        )
        d_sp = keep(g_sp, d_sp)
        d_hp = keep(g_hp, d_hp)
        loss_acc = loss_acc + jnp.where(
            jnp.logical_and(b_valid, is_last), loss_b, 0.0
        )
        ce_acc = ce_acc + jnp.where(
            jnp.logical_and(b_valid, is_last), ce_b, 0.0
        )
        aux_acc = aux_acc + jnp.where(b_valid, _aux_b, 0.0)
        # Cotangent rides to stage i-1 (same microbatch there next tick);
        # zero when invalid so bubbles cannot inject garbage.
        bwd_cot = jnp.where(b_valid, g_act, jnp.zeros_like(g_act))
        # Stage 0's activation cotangent is the embedding's.
        dx_cur = jax.lax.dynamic_index_in_dim(dx, slot_b_full(m_b), 0,
                                              keepdims=False)
        dx = jax.lax.dynamic_update_index_in_dim(
            dx,
            jnp.where(
                jnp.logical_and(b_valid, index == 0), g_act, dx_cur
            ),
            slot_b_full(m_b),
            0,
        )
        return (
            fwd_state, bwd_cot, acts, d_sp, d_hp, dx,
            loss_acc, ce_acc, aux_acc,
        ), None

    def slot_b_full(m_b):
        return jnp.clip(m_b, 0, n_micro - 1)

    varying = lambda x: jax.lax.pcast(x, (axis_name,), to="varying")  # noqa: E731
    zeros_like_tree = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda x: varying(jnp.zeros(x.shape, x.dtype)), t
    )
    carry0 = (
        varying(jnp.zeros(mb_shape, dtype)),                      # fwd_state
        varying(jnp.zeros(mb_shape, dtype)),                      # bwd_cot
        varying(jnp.zeros((ring, *mb_shape), dtype)),             # acts
        zeros_like_tree(stage_params),                            # d_sp
        zeros_like_tree(head_params),                             # d_hp
        varying(jnp.zeros((n_micro, *mb_shape), dtype)),          # dx
        varying(jnp.zeros((), jnp.float32)),                      # loss
        varying(jnp.zeros((), jnp.float32)),                      # ce
        varying(jnp.zeros((), jnp.float32)),                      # aux
    )
    (_, _, _, d_sp, d_hp, dx, loss, ce, aux), _ = jax.lax.scan(
        tick, carry0, jnp.arange(total_ticks)
    )
    return loss, ce, aux, d_sp, d_hp, dx
