"""Pipeline parallelism: GPipe-style microbatch schedule on the ``pp`` axis.

Written for per-device SPMD code (inside ``shard_map``): each pipeline stage
holds its slice of the layer stack; activations hop stage→stage with
``ppermute`` while microbatches stream through, so at steady state every
stage computes every step.  The backward pass falls out of JAX's transpose
of the scan+ppermute (reverse schedule) — correct, and good enough until a
hand-tuned 1F1B schedule lands.

The schedule runs ``n_micro + n_stages - 1`` steps; device ``i`` works on
microbatch ``step - i`` when that index is valid.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def gpipe_spmd(
    stage_fn: Callable,
    stage_params,
    x_microbatches: jax.Array,
    axis_name: str = "pp",
):
    """Run the pipeline inside shard_map.

    Args:
      stage_fn: ``(stage_params, activation) -> activation`` for one stage's
        layer stack; activation shape ``[mb, ...]`` must be preserved.
      stage_params: THIS stage's parameters (already sliced by shard_map).
      x_microbatches: ``[n_micro, mb, ...]`` — the stage-0 input stream
        (replicated over ``pp``; only stage 0 reads it).
      axis_name: the pipeline mesh axis.

    Returns ``[n_micro, mb, ...]`` final-stage outputs, replicated to every
    stage via a single psum at the end (simple and correct; the heavier
    broadcast is amortized over the whole step).
    """
    size = jax.lax.axis_size(axis_name)
    index = jax.lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    total_steps = n_micro + size - 1

    perm = [(i, (i + 1) % size) for i in range(size)]
    out_dtype = jax.eval_shape(
        lambda p, a: stage_fn(p, a), stage_params, x_microbatches[0]
    ).dtype

    def step(carry, step_idx):
        state, outputs = carry
        # Activation arriving from the previous stage.
        received = jax.lax.ppermute(state, axis_name, perm)
        feed_idx = jnp.clip(step_idx, 0, n_micro - 1)
        stage0_in = jax.lax.dynamic_index_in_dim(
            x_microbatches, feed_idx, axis=0, keepdims=False
        ).astype(out_dtype)
        my_input = jnp.where(index == 0, stage0_in, received)
        state = stage_fn(stage_params, my_input)
        # The last stage emits microbatch (step - size + 1) when valid.
        out_idx = step_idx - (size - 1)
        is_valid = jnp.logical_and(index == size - 1, out_idx >= 0)
        write_idx = jnp.clip(out_idx, 0, n_micro - 1)
        current = jax.lax.dynamic_index_in_dim(
            outputs, write_idx, axis=0, keepdims=False
        )
        updated = jnp.where(is_valid, state, current)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, updated, write_idx, axis=0
        )
        return (state, outputs), None

    # The carry varies per pipeline stage; mark the zero inits accordingly
    # (shard_map VMA typing).
    state0 = jax.lax.pcast(
        jnp.zeros(mb_shape, dtype=out_dtype), (axis_name,), to="varying"
    )
    outputs0 = jax.lax.pcast(
        jnp.zeros((n_micro, *mb_shape), dtype=out_dtype),
        (axis_name,),
        to="varying",
    )
    (_, outputs), _ = jax.lax.scan(
        step, (state0, outputs0), jnp.arange(total_steps)
    )
    # Only the last stage holds real outputs; share them with every stage so
    # the loss (and its gradient) is computed identically everywhere.
    mask = (index == size - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * mask, axis_name)
