"""Pipeline parallelism: GPipe-style microbatch schedule on the ``pp`` axis.

Written for per-device SPMD code (inside ``shard_map``): each pipeline stage
holds its slice of the layer stack; activations hop stage→stage with
``ppermute`` while microbatches stream through, so at steady state every
stage computes every step.  The backward pass falls out of JAX's transpose
of the scan+ppermute (reverse schedule).

Memory: with ``stage_remat`` (default) each schedule step stores only its
stage *input* for the backward and recomputes the stage's layers — peak
activation memory drops from O(steps · layers_per_stage) to O(steps)
activations per device.  A hand-interleaved 1F1B schedule (forward and
backward of different microbatches in the same tick) cannot be expressed
through plain autodiff — it would require the pipeline to own its backward
(explicit per-microbatch vjp with cotangents ppermuted stage→stage-1);
planned future work.

The schedule runs ``n_micro + n_stages - 1`` steps; device ``i`` works on
microbatch ``step - i`` when that index is valid.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def gpipe_spmd(
    stage_fn: Callable,
    stage_params,
    x_microbatches: jax.Array,
    axis_name: str = "pp",
    stage_remat: bool = True,
):
    """Run the pipeline inside shard_map.

    Args:
      stage_fn: ``(stage_params, activation) -> (activation, aux)`` for one
        stage's layer stack; activation shape ``[mb, ...]`` must be
        preserved, ``aux`` is a scalar auxiliary loss (e.g. MoE load
        balancing) summed over the stage's layers.
      stage_params: THIS stage's parameters (already sliced by shard_map).
      x_microbatches: ``[n_micro, mb, ...]`` — the stage-0 input stream
        (replicated over ``pp``; only stage 0 reads it).
      axis_name: the pipeline mesh axis.
      stage_remat: rematerialize the stage in the backward instead of
        storing every layer's activations per schedule step.

    Returns ``(outputs, aux)``: outputs ``[n_micro, mb, ...]`` are REAL ONLY
    ON THE LAST STAGE (zeros elsewhere — the caller's loss must mask to the
    last stage, which also keeps replicated-param gradients single-sourced);
    ``aux`` is the mean-over-microbatches auxiliary loss, psum'd over the
    pipeline axis (bubble steps are masked out).
    """
    size = jax.lax.axis_size(axis_name)
    index = jax.lax.axis_index(axis_name)
    n_micro = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    total_steps = n_micro + size - 1

    if stage_remat:
        stage_fn = jax.checkpoint(stage_fn)

    perm = [(i, (i + 1) % size) for i in range(size)]
    out_shape, _ = jax.eval_shape(
        lambda p, a: stage_fn(p, a), stage_params, x_microbatches[0]
    )
    out_dtype = out_shape.dtype

    def step(carry, step_idx):
        state, outputs, aux_sum = carry
        # Activation arriving from the previous stage.
        received = jax.lax.ppermute(state, axis_name, perm)
        feed_idx = jnp.clip(step_idx, 0, n_micro - 1)
        stage0_in = jax.lax.dynamic_index_in_dim(
            x_microbatches, feed_idx, axis=0, keepdims=False
        ).astype(out_dtype)
        my_input = jnp.where(index == 0, stage0_in, received)
        state, aux = stage_fn(stage_params, my_input)
        # Bubble steps compute on garbage; count aux only when this stage
        # holds a real microbatch (step - index ∈ [0, n_micro)).
        mb_idx = step_idx - index
        is_real = jnp.logical_and(mb_idx >= 0, mb_idx < n_micro)
        aux_sum = aux_sum + jnp.where(is_real, aux, 0.0)
        # The last stage emits microbatch (step - size + 1) when valid.
        out_idx = step_idx - (size - 1)
        is_valid = jnp.logical_and(index == size - 1, out_idx >= 0)
        write_idx = jnp.clip(out_idx, 0, n_micro - 1)
        current = jax.lax.dynamic_index_in_dim(
            outputs, write_idx, axis=0, keepdims=False
        )
        updated = jnp.where(is_valid, state, current)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, updated, write_idx, axis=0
        )
        return (state, outputs, aux_sum), None

    # The carry varies per pipeline stage; mark the zero inits accordingly
    # (shard_map VMA typing).
    state0 = jax.lax.pcast(
        jnp.zeros(mb_shape, dtype=out_dtype), (axis_name,), to="varying"
    )
    outputs0 = jax.lax.pcast(
        jnp.zeros((n_micro, *mb_shape), dtype=out_dtype),
        (axis_name,),
        to="varying",
    )
    aux0 = jax.lax.pcast(
        jnp.zeros((), jnp.float32), (axis_name,), to="varying"
    )
    (_, outputs, aux_sum), _ = jax.lax.scan(
        step, (state0, outputs0, aux0), jnp.arange(total_steps)
    )
    # Each stage saw every microbatch once; aggregate the per-stage layer
    # contributions and average over microbatches to match the non-pp path.
    aux = jax.lax.psum(aux_sum, axis_name) / n_micro
    return outputs, aux
