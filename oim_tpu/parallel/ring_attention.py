"""Ring attention: exact attention over sequence shards on a ring.

Long-context/sequence parallelism for the framework (SURVEY.md §2.3): each
device of the ``sp`` axis holds a sequence block of Q, K, V; K/V blocks
rotate around the ring via ``ppermute`` while every device accumulates its
queries' attention with a numerically-stable online softmax (flash-attention
style running max/denominator).  After ``sp`` steps every Q block has seen
every K/V block — exact attention, O(T/sp) memory per chip, and the
rotation overlaps with compute on ICI neighbor links (the XLA latency-hiding
scheduler overlaps the collective-permute with the einsums).

Used inside ``shard_map`` with the sequence dimension sharded over the ring
axis (blockwise ring attention per Liu et al., implemented from scratch for
this framework).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

_NEG_BIG = -1e30  # finite "-inf": keeps fully-masked rows NaN-free


def _block_attention(
    q, k, v, m, l, o, q_offset, k_offset, causal, scale,
    seg_q=None, seg_k=None, window=0,
):
    """One flash-style accumulation step of local q against one k/v block.

    Grouped-query form (classic MHA is group size 1):
    q: [B, Tq, KVH, G, D]; k, v: [B, Tk, KVH, D]
    m, l: [B, KVH, G, Tq]; o: [B, Tq, KVH, G, D]
    (running max / denominator / numerator)
    seg_q [B, Tq] / seg_k [B, Tk] mask cross-segment pairs (packing).
    """
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(tq)[:, None]
        k_pos = k_offset + jnp.arange(tk)[None, :]
        keep = q_pos >= k_pos
        if window:
            keep &= q_pos - k_pos < window
        scores = jnp.where(keep, scores, _NEG_BIG)
    if seg_q is not None:
        same = seg_q[:, :, None] == seg_k[:, None, :]  # [B, Tq, Tk]
        scores = jnp.where(same[:, None, None], scores, _NEG_BIG)
    block_max = jnp.max(scores, axis=-1)  # [B, KVH, G, Tq]
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m[..., None])  # [B, KVH, G, Tq, Tk]
    new_l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhgqk,bkhd->bqhgd", p, v)
    new_o = o * correction.transpose(0, 3, 1, 2)[..., None] + pv
    return new_m, new_l, new_o


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    segments: jax.Array | None = None,
    window: int = 0,
) -> jax.Array:
    """Exact attention over a ring of sequence shards.

    Args:
      q, k, v: local shards ``[batch, seq_local, heads, head_dim]``; the
        global sequence is the concatenation over the ``axis_name`` ring in
        axis-index order.
      axis_name: mesh axis carrying the sequence shards (``sp``).
      causal: standard causal masking in *global* positions.
      segments: local ``[batch, seq_local]`` segment-id shard (sequence
        packing) — rotates around the ring with its k/v block so
        cross-document pairs are masked across shard boundaries too.
      window: sliding-window attention (each query sees the last
        ``window`` global positions; causal only).  The ring always
        rotates, but hops whose block is fully masked (entirely in the
        causal future, or entirely below the window) skip their matmuls
        via ``lax.cond`` — windowed long-context training is O(T·W)
        under sp too.

    Returns the local output shard ``[batch, seq_local, heads, head_dim]``.
    """
    if window and not causal:
        raise ValueError("sliding window requires causal attention")
    size = jax.lax.axis_size(axis_name)
    index = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    kvh = k.shape[2]
    if h % kvh:
        raise ValueError(f"n_heads {h} not divisible by n_kv_heads {kvh}")
    group = h // kvh
    scale = 1.0 / (d**0.5)
    dtype = q.dtype
    # Accumulate in f32 regardless of input dtype (bf16-safe softmax).
    # GQA: q is grouped per kv head and the RING CARRIES KV-SIZED BLOCKS —
    # the rotation traffic shrinks by n_heads/n_kv_heads (classic MHA is
    # simply group size 1 through the same path).
    qf = q.astype(jnp.float32).reshape(b, t_local, kvh, group, d)
    kf, vf = k.astype(jnp.float32), v.astype(jnp.float32)

    # Derive the accumulator inits from q (zeroed) rather than jnp.zeros:
    # under shard_map the carry must have the same varying-manual-axes type
    # as the loop outputs, and inheriting q's does that on any jax version.
    zero_stats = jnp.moveaxis(qf, 1, 3)[..., 0] * 0.0  # [B, KVH, G, Tq]
    m0 = zero_stats + _NEG_BIG
    l0 = zero_stats
    o0 = qf * 0.0
    q_offset = index * t_local

    seg_local = (
        None if segments is None else segments.astype(jnp.int32)
    )

    def step(carry, step_idx):
        m, l, o, k_blk, v_blk, seg_blk = carry
        # The k/v block currently held started at ring position
        # (index - step) mod size.
        k_owner = (index - step_idx) % size
        k_offset = k_owner * t_local

        def attend(operands):
            m_, l_, o_, kb, vb, sb = operands
            return _block_attention(
                qf, kb, vb, m_, l_, o_, q_offset, k_offset, causal, scale,
                seg_local, sb, window,
            )

        if causal:
            # Hops whose k/v block is fully masked carry zero mass —
            # keep rotating, skip the matmuls.  Entirely-future blocks
            # are dead for any causal run (~half the hops on the ring);
            # with a window, entirely-below-window blocks are too, which
            # makes windowed long-context training O(T·W) under sp just
            # like the flash kernel.  Hop 0 (the self block) is always
            # attended, so the online softmax never starts on a skip.
            relevant = k_offset <= q_offset + t_local - 1  # not future
            if window:
                relevant = jnp.logical_and(
                    relevant,
                    q_offset - (k_offset + t_local - 1) < window,
                )
            m, l, o = jax.lax.cond(
                relevant,
                attend,
                lambda operands: operands[:3],
                (m, l, o, k_blk, v_blk, seg_blk),
            )
        else:
            m, l, o = attend((m, l, o, k_blk, v_blk, seg_blk))
        # Rotate k/v one hop around the ring (neighbor traffic on ICI).
        perm = [(i, (i + 1) % size) for i in range(size)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        seg_next = (
            None if seg_blk is None
            else jax.lax.ppermute(seg_blk, axis_name, perm)
        )
        return (m, l, o, k_next, v_next, seg_next), None

    (m, l, o, _, _, _), _ = jax.lax.scan(
        step, (m0, l0, o0, kf, vf, seg_local), jnp.arange(size)
    )
    # Fully-masked rows (can only happen for non-causal degenerate inputs)
    # keep l == 0; guard the division.
    denom = jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    return (o / denom).reshape(b, t_local, h, d).astype(dtype)


# The O(T²) correctness oracle lives in oim_tpu.ops (one canonical copy).
from oim_tpu.ops.flash_attention import reference_attention  # noqa: E402

__all__ = ["reference_attention", "ring_attention", "ring_attention_sharded"]


def ring_attention_sharded(
    q, k, v, mesh, causal: bool = True, rules=None, segments=None,
    window: int = 0,
):
    """Convenience wrapper: global arrays in, global arrays out, with the
    sequence dimension sharded over ``sp`` and batch over ``dp``
    (``segments`` [B, T] shards the same way)."""
    from jax.sharding import PartitionSpec as P

    spec = P("dp", "sp", None, None)
    if segments is None:
        fn = jax.shard_map(
            partial(ring_attention, axis_name="sp", causal=causal,
                    window=window),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        return fn(q, k, v)
    fn = jax.shard_map(
        lambda q_, k_, v_, s_: ring_attention(
            q_, k_, v_, "sp", causal=causal, segments=s_, window=window
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, P("dp", "sp")),
        out_specs=spec,
    )
    return fn(q, k, v, segments)
