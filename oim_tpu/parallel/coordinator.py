"""JAX distributed bootstrap from the CSI-staged config.

The node server stages ``tpu-bootstrap.json`` next to the device files
(oim_tpu/csi/mounter.py) — the TPU analog of the mounted filesystem the
reference's NodeStage produced.  A workload calls ``initialize()`` first
thing; on multi-host slices this brings up the JAX distributed coordinator
(the role the reference's virtio-scsi hotplug + mount played is here
"PJRT client ready + process group formed").
"""

from __future__ import annotations

import json
import os
import re
import sys
from dataclasses import dataclass, field

from oim_tpu import log

DEFAULT_BOOTSTRAP_PATH = "/tpu/tpu-bootstrap.json"

_ACCEL_RE = re.compile(r"^/dev/accel(\d+)$")
_PJRT_RE = re.compile(r"^pjrt:(\d+)$")


def _jax_backends_initialized() -> bool:
    """True iff a JAX backend is already live (binding would be too late).
    jax being merely *imported* is fine — libtpu reads the env at backend
    init, not import."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:
        return True  # unknown internals: assume the worst, warn


@dataclass
class Bootstrap:
    volume_id: str = ""
    chips: list[dict] = field(default_factory=list)
    mesh: list[int] = field(default_factory=list)
    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = 0

    @property
    def chip_count(self) -> int:
        return len(self.chips)


def load_bootstrap(path: str = "") -> Bootstrap:
    """Read the staged bootstrap file.  Search order: explicit path, the
    ``TPU_BOOTSTRAP`` env var, the conventional pod mount point."""
    path = path or os.environ.get("TPU_BOOTSTRAP", "") or DEFAULT_BOOTSTRAP_PATH
    with open(path) as f:
        data = json.load(f)
    return Bootstrap(
        volume_id=data.get("volume_id", ""),
        chips=data.get("chips", []),
        mesh=list(data.get("mesh", [])),
        coordinator_address=data.get("coordinator_address", ""),
        num_processes=int(data.get("num_processes", 1)),
        process_id=int(data.get("process_id", 0)),
    )


def chip_binding_env(bootstrap: Bootstrap) -> dict[str, str]:
    """libtpu/PJRT env restricting a JAX process to the volume's chips.

    The reference's whole point was that the attach handed the workload
    *its* device at a specific BDF (remote.go:249-290 waits for exactly
    that device to appear); the TPU analog is the staged chip set — without
    it, a pod on a multi-tenant host would initialize every chip on the
    host.  Chip indices come from the staged device paths (``/dev/accelN``
    in real mode, ``pjrt:N`` in chips-from-pjrt mode).  Returns ``{}`` for
    fake/stub devices (CPU test fixtures), where there is nothing to bind.
    """
    indices = []
    for chip in bootstrap.chips:
        path = chip.get("device_path", "")
        m = _ACCEL_RE.match(path) or _PJRT_RE.match(path)
        if m is None:
            return {}
        indices.append(int(m.group(1)))
    if not indices:
        return {}
    env = {
        "TPU_VISIBLE_CHIPS": ",".join(str(i) for i in sorted(indices)),
    }
    if bootstrap.mesh and bootstrap.num_processes <= 1:
        # Single-process sub-host slice: tell libtpu the slice topology so
        # it builds the allocation's mesh, not the host's.  Multi-host
        # process layout is the distributed coordinator's job — the
        # per-process bounds would be wrong to guess here.
        dims = (list(bootstrap.mesh) + [1, 1, 1])[:3]
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = ",".join(str(d) for d in dims)
        env["TPU_PROCESS_BOUNDS"] = "1,1,1"
    return env


def apply_chip_binding(bootstrap: Bootstrap) -> dict[str, str]:
    """Export the binding env (must run BEFORE jax/libtpu initialize).

    Returns what was applied ({} when the staged devices are fakes).  If
    jax is already imported the binding may be too late to matter — that is
    a workload bug, so it is warned about loudly rather than hidden.
    """
    env = chip_binding_env(bootstrap)
    if not env:
        log.current().debug(
            "no chip binding applied (fake/stub device paths)",
            volume=bootstrap.volume_id,
        )
        return env
    if _jax_backends_initialized():
        # Importing jax is fine (env is read at backend init, and this
        # package itself imports jax) — an already-initialized backend is
        # the genuinely-too-late case: libtpu has claimed its chips.
        log.current().warning(
            "apply_chip_binding after the JAX backend initialized: libtpu "
            "already owns its chips; bind before the first device touch"
        )
    os.environ.update(env)
    log.current().info(
        "chip binding applied", volume=bootstrap.volume_id, **env
    )
    return env


def initialize_distributed(bootstrap: Bootstrap) -> None:
    """Form the multi-host process group when the slice spans hosts.

    Single-host volumes skip coordination entirely (the common case for
    sub-host slices); multi-host volumes rendezvous at the coordinator the
    controller allocated (MapVolumeReply.coordinator_address) — the registry
    KV picked one coordinator per volume, so every host's bootstrap agrees.
    """
    if bootstrap.num_processes <= 1:
        log.current().debug("single-process slice; skipping jax.distributed")
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=bootstrap.coordinator_address,
        num_processes=bootstrap.num_processes,
        process_id=bootstrap.process_id,
    )
    log.current().info(
        "jax distributed initialized",
        coordinator=bootstrap.coordinator_address,
        process=f"{bootstrap.process_id}/{bootstrap.num_processes}",
    )


def initialize(path: str = "", **mesh_kwargs):
    """One-call workload entry: read bootstrap, bind to the staged chips,
    join the process group, return the logical mesh.  ``mesh_kwargs`` are
    the pp/sp/tp/ep sizes for ``mesh_from_bootstrap``."""
    from oim_tpu.parallel.mesh import mesh_from_bootstrap

    bootstrap = load_bootstrap(path)
    apply_chip_binding(bootstrap)
    initialize_distributed(bootstrap)
    return mesh_from_bootstrap(bootstrap, **mesh_kwargs)
