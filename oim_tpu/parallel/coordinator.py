"""JAX distributed bootstrap from the CSI-staged config.

The node server stages ``tpu-bootstrap.json`` next to the device files
(oim_tpu/csi/mounter.py) — the TPU analog of the mounted filesystem the
reference's NodeStage produced.  A workload calls ``initialize()`` first
thing; on multi-host slices this brings up the JAX distributed coordinator
(the role the reference's virtio-scsi hotplug + mount played is here
"PJRT client ready + process group formed").
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from oim_tpu import log

DEFAULT_BOOTSTRAP_PATH = "/tpu/tpu-bootstrap.json"


@dataclass
class Bootstrap:
    volume_id: str = ""
    chips: list[dict] = field(default_factory=list)
    mesh: list[int] = field(default_factory=list)
    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = 0

    @property
    def chip_count(self) -> int:
        return len(self.chips)


def load_bootstrap(path: str = "") -> Bootstrap:
    """Read the staged bootstrap file.  Search order: explicit path, the
    ``TPU_BOOTSTRAP`` env var, the conventional pod mount point."""
    path = path or os.environ.get("TPU_BOOTSTRAP", "") or DEFAULT_BOOTSTRAP_PATH
    with open(path) as f:
        data = json.load(f)
    return Bootstrap(
        volume_id=data.get("volume_id", ""),
        chips=data.get("chips", []),
        mesh=list(data.get("mesh", [])),
        coordinator_address=data.get("coordinator_address", ""),
        num_processes=int(data.get("num_processes", 1)),
        process_id=int(data.get("process_id", 0)),
    )


def initialize_distributed(bootstrap: Bootstrap) -> None:
    """Form the multi-host process group when the slice spans hosts.

    Single-host volumes skip coordination entirely (the common case for
    sub-host slices); multi-host volumes rendezvous at the coordinator the
    controller allocated (MapVolumeReply.coordinator_address) — the registry
    KV picked one coordinator per volume, so every host's bootstrap agrees.
    """
    if bootstrap.num_processes <= 1:
        log.current().debug("single-process slice; skipping jax.distributed")
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=bootstrap.coordinator_address,
        num_processes=bootstrap.num_processes,
        process_id=bootstrap.process_id,
    )
    log.current().info(
        "jax distributed initialized",
        coordinator=bootstrap.coordinator_address,
        process=f"{bootstrap.process_id}/{bootstrap.num_processes}",
    )


def initialize(path: str = "", **mesh_kwargs):
    """One-call workload entry: read bootstrap, join the process group,
    return the logical mesh.  ``mesh_kwargs`` are the pp/sp/tp/ep sizes for
    ``mesh_from_bootstrap``."""
    from oim_tpu.parallel.mesh import mesh_from_bootstrap

    bootstrap = load_bootstrap(path)
    initialize_distributed(bootstrap)
    return mesh_from_bootstrap(bootstrap, **mesh_kwargs)
