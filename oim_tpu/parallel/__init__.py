"""The JAX compute path: meshes, shardings, collectives, parallelism.

This is the layer that runs ON the slices the control plane provisions
(SURVEY.md §2.3: the parallelism inventory the TPU build introduces as new
work).  Everything is TPU-first: SPMD over a `jax.sharding.Mesh` with XLA
collectives riding ICI, `shard_map` for explicitly-scheduled parallelism
(ring attention, pipelining), GSPMD sharding constraints elsewhere.
"""

from oim_tpu.parallel.mesh import AXES, build_mesh, mesh_from_bootstrap
from oim_tpu.parallel.sharding import (
    ShardingRules,
    DEFAULT_RULES,
    partition_spec,
    named_sharding,
    constrain,
)
from oim_tpu.parallel.coordinator import (
    Bootstrap,
    apply_chip_binding,
    chip_binding_env,
    initialize,
    initialize_distributed,
    load_bootstrap,
)
from oim_tpu.parallel.ring_attention import ring_attention
from oim_tpu.parallel.ulysses import ulysses_attention
from oim_tpu.parallel import collectives

__all__ = [
    "AXES",
    "build_mesh",
    "mesh_from_bootstrap",
    "ShardingRules",
    "DEFAULT_RULES",
    "partition_spec",
    "named_sharding",
    "constrain",
    "Bootstrap",
    "apply_chip_binding",
    "chip_binding_env",
    "initialize",
    "initialize_distributed",
    "load_bootstrap",
    "ring_attention",
    "ulysses_attention",
    "collectives",
]
