"""Collective wrappers + the ICI bandwidth harness.

The distributed communication backend of the framework (SURVEY.md §2.3): XLA
collectives over ICI within a slice and DCN across slices — the role
NCCL/MPI plays in GPU stacks.  The wrappers exist for a stable API surface
and for the benchmark harness behind BASELINE.md's ≥90%-of-line-rate
all-reduce target; inside, they are the primitive `jax.lax` collectives that
XLA lowers straight onto the torus.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name):
    return jax.lax.pmean(x, axis_name)


def all_gather(x, axis_name, axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis: int = 0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ppermute_shift(x, axis_name, shift: int = 1):
    """Rotate shards around the ring by ``shift`` (the ring-attention /
    pipeline primitive)."""
    size = jax.lax.axis_size(axis_name)
    perm = [(i, (i + shift) % size) for i in range(size)]
    return jax.lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis: int, concat_axis: int):
    return jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


# ---------------------------------------------------------------------------
# Bandwidth harness (BASELINE.md: ICI all-reduce GB/s/chip)


def allreduce_bandwidth(
    mesh: Mesh,
    axis: str = "dp",
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 10,
    warmup: int = 3,
) -> dict:
    """Time an all-reduce over ``axis`` and report achieved GB/s per chip.

    Bus bandwidth convention (matches NCCL's): for an all-reduce over n
    devices, each chip moves 2*(n-1)/n × payload bytes over its links.
    """
    n = mesh.shape[axis]
    elem = jnp.dtype(dtype).itemsize
    per_device_elems = int(size_mb * 1e6 / elem)
    # Lane-friendly shape: (k, 128) keeps the VPU/ICI path dense.
    rows = max(per_device_elems // 128, 1)
    global_shape = (rows * n, 128)

    sharding = NamedSharding(mesh, P(axis, None))
    x = jax.device_put(
        jnp.ones(global_shape, dtype=dtype), sharding
    )

    @partial(
        jax.jit,
        out_shardings=sharding,
    )
    def step(v):
        # psum over a mesh axis expressed via GSPMD: sum of all shards,
        # result re-sharded — an all-reduce on the wire.
        summed = jax.shard_map(
            lambda s: jax.lax.psum(s, axis),
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=P(axis, None),
        )(v)
        return summed

    for _ in range(warmup):
        step(x).block_until_ready()
    start = time.perf_counter()
    for _ in range(iters):
        x = step(x)
    x.block_until_ready()
    elapsed = (time.perf_counter() - start) / iters

    payload_bytes = rows * 128 * elem  # per-chip shard
    bus_bytes = 2 * (n - 1) / n * payload_bytes
    return {
        "axis": axis,
        "devices": n,
        "payload_mb": payload_bytes / 1e6,
        "seconds": elapsed,
        "gbps_per_chip": bus_bytes / elapsed / 1e9,
    }


def matmul_throughput(
    m: int = 4096,
    k: int = 4096,
    n: int = 4096,
    dtype=jnp.bfloat16,
    iters: int = 20,
    warmup: int = 5,
) -> dict:
    """Single-chip MXU throughput probe (TFLOP/s) — the compute-side
    companion to the ICI harness, used by bench.py on the real chip."""
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (m, k), dtype=dtype)
    b = jax.random.normal(key, (k, n), dtype=dtype)

    @jax.jit
    def mm(a, b):
        return a @ b

    for _ in range(warmup):
        mm(a, b).block_until_ready()
    start = time.perf_counter()
    for _ in range(iters):
        out = mm(a, b)
    out.block_until_ready()
    elapsed = (time.perf_counter() - start) / iters
    flops = 2.0 * m * k * n
    return {"m": m, "k": k, "n": n, "seconds": elapsed, "tflops": flops / elapsed / 1e12}
