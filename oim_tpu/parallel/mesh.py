"""Logical device meshes over the physical ICI topology.

The control plane hands a workload its physical mesh shape (the
``MapVolumeReply.mesh`` / bootstrap ``mesh`` field — the actual ICI torus of
the allocated sub-slice); this module folds it into the canonical logical
axes used throughout the framework:

    dp   data parallelism        (batch)
    pp   pipeline parallelism    (layer stages)
    sp   sequence parallelism    (ring attention / context)
    tp   tensor parallelism      (heads / mlp / vocab)
    ep   expert parallelism      (MoE experts)

Axis order is outermost-first: ICI neighbor traffic is heaviest for tp/sp
collectives, so those sit innermost where `jax.experimental.mesh_utils`-style
device orderings keep them on adjacent chips.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "pp", "sp", "tp", "ep")


def build_mesh(
    dp: int = 1,
    pp: int = 1,
    sp: int = 1,
    tp: int = 1,
    ep: int = 1,
    devices=None,
) -> Mesh:
    """A mesh with the canonical five axes (size-1 axes are fine and cost
    nothing — shardings over them are no-ops)."""
    sizes = {"dp": dp, "pp": pp, "sp": sp, "tp": tp, "ep": ep}
    for name, size in sizes.items():
        if size < 1:
            raise ValueError(f"{name}={size} must be >= 1")
    n = math.prod(sizes.values())
    if devices is None:
        devices = jax.devices()
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(*(sizes[a] for a in AXES))
    return Mesh(arr, AXES)


def mesh_from_bootstrap(
    bootstrap,
    dp: int = 0,
    pp: int = 1,
    sp: int = 1,
    tp: int = 1,
    ep: int = 1,
    devices=None,
) -> Mesh:
    """Build the logical mesh for a CSI-provisioned slice.

    ``dp=0`` (default) absorbs the leftover: dp = n_chips // (pp*sp*tp*ep),
    so a workload can say "tp=4, everything else data-parallel" regardless of
    slice size.

    The bootstrap's ``mesh`` is the *local* (per-host) sub-slice; on a
    multi-host volume the global device count is local × num_processes, and
    after ``jax.distributed.initialize`` (oim_tpu.parallel.coordinator)
    ``jax.devices()`` already returns all of them.
    """
    local = math.prod(bootstrap.mesh) if bootstrap.mesh else len(bootstrap.chips)
    n = local * max(1, getattr(bootstrap, "num_processes", 1))
    fixed = pp * sp * tp * ep
    if dp == 0:
        if n % fixed != 0:
            raise ValueError(
                f"slice of {n} chips not divisible by pp*sp*tp*ep={fixed}"
            )
        dp = n // fixed
    if dp * fixed != n:
        raise ValueError(
            f"dp*pp*sp*tp*ep={dp * fixed} does not match slice size {n}"
        )
    return build_mesh(dp=dp, pp=pp, sp=sp, tp=tp, ep=ep, devices=devices)
