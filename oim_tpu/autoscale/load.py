"""Serving-load telemetry: the ``load/<cn>`` registry keyspace.

The autoscaler's observation plane.  Each serving instance publishes ONE
leased key, ``load/<its full TLS CommonName>`` (``load/serve.<id>`` for
oim-serve), beside its ``serve/<id>/address`` discovery heartbeat.  The
value is a compact JSON snapshot of the engine's live pressure — queue
depth, busy/total slots, the marginal token-rate EWMA, shed counters,
the brownout flag — exactly the fields ``GET /v1/info`` mirrors under
``load`` for the router.  The lease (3x the heartbeat period, like the
address key) means a crashed instance's stale load expires with a watch
event instead of pinning the fleet's utilization estimate forever.

Authorization follows the flight-recorder precedent (``events/{cn}/*``):
any authenticated peer may write exactly its own ``load/<cn>`` key —
one compromised backend can lie about its *own* pressure but cannot
forge a sibling's (registry/authz.py ``AUTHZ_GRANTS``).

Schema discipline matches health/states.py: ``decode_load`` never
raises on malformed or foreign values — a watcher must not die on one
bad key — and fills defaults so consumers index fields unconditionally.
"""

from __future__ import annotations

import json
from typing import Any

LOAD_PREFIX = "load"

# Fields every decoded snapshot carries (and their defaults): consumers
# (the autoscaler's utilization math, the router's /v1/stats) index
# these unconditionally.
_DEFAULTS: dict[str, Any] = {
    "queue_depth": 0,
    "active_slots": 0,
    "total_slots": 0,
    # Paged-KV headroom (ISSUE 10; zeros from dense engines and from
    # publishers predating the fields — the tolerant-decode defaults):
    # which replica is out of CACHE, not just out of slots.
    "kv_blocks_total": 0,
    "kv_blocks_free": 0,
    "kv_blocks_shared": 0,
    "kv_fragmentation": 0.0,
    # Fast-path discovery (ISSUE 13; False from publishers predating
    # the fields): whether the backend decodes through the paged
    # flash kernel, and whether its cache runs the kv4 quant rung —
    # `oimctl top` and the router surface both so an operator can see
    # which replicas run the fast path.
    "paged_kernel": False,
    "kv_int4": False,
    # Chunked flash-prefill (ISSUE 20; False/zeros from publishers
    # predating the fields — tolerant-decode defaults): whether the
    # backend prefills through the block-pool flash kernel, its
    # segment size (0 = one-shot admission), and the cumulative
    # prompt-segment dispatch count — the fleet view of long-prompt
    # admission pressure.
    "prefill_kernel": False,
    "prefill_chunk": 0,
    "prefill_segments": 0,
    # Disaggregated prefill/decode (ISSUE 12; "mixed"/zeros from
    # pre-disaggregation publishers via the tolerant-decode defaults):
    # which POOL this backend serves, and its share of the fleet's
    # KV-ship traffic (exports served / ingests staged / bytes both
    # ways) — the per-pool watermark policy and `oimctl top`'s pool
    # column both key on these.
    "pool": "mixed",
    "kv_exports": 0,
    "kv_imports": 0,
    "kv_ship_bytes": 0,
    # Fleet prefix residency (ISSUE 14; empty/zeros from publishers
    # predating the fields — tolerant-decode defaults): the capped,
    # hottest-first resident-digest summary the router's residency map
    # and the autoscaler's bring-up pre-warm read, plus the hit/miss
    # counters the fleet prefix-hit rate aggregates.  The summary is
    # truncated at the ENGINE (disagg.PREFIX_DIGEST_CAP) so this
    # leased value stays small however large the cache grows.
    "prefix_digests": [],
    "prefix_hits": 0,
    "prefix_misses": 0,
    # Host-RAM KV overflow tier (ISSUE 15; zeros from dense engines,
    # tier-less engines, and publishers predating the fields — the
    # tolerant-decode defaults): the second capacity tier's headroom,
    # the demote/promote movement counters (`oimctl top`'s PROMO
    # column; promote ≈ demote at high kv_fragmentation is the thrash
    # signature), parked-slot count, and the demote-vs-evict split so
    # a capacity incident can tell "moved to host" from "lost
    # forever".
    "kv_host_blocks_total": 0,
    "kv_host_blocks_free": 0,
    "kv_host_fragmentation": 0.0,
    "kv_demotions": 0,
    "kv_promotions": 0,
    "parked_slots": 0,
    "prefix_demotions": 0,
    "prefix_evictions": 0,
    # KV-tier flow telemetry (ISSUE 18; zeros from publishers predating
    # the fields — tolerant-decode defaults): park/restore counts plus
    # per-direction wall seconds and bytes, so the fleet view (`oimctl
    # kv`) and cache-aware autoscaling (ROADMAP item 5) can read tier
    # bandwidth and thrash rates off the same leased load key.
    "kv_parks": 0,
    "kv_unparks": 0,
    "kv_demote_seconds": 0.0,
    "kv_promote_seconds": 0.0,
    "kv_demote_bytes": 0,
    "kv_promote_bytes": 0,
    "token_rate": 0.0,
    "shed_queue_full": 0,
    "shed_deadline": 0,
    "shed_brownout": 0,
    "brownout": False,
    # Multi-tenant QoS (ISSUE 16; empty/zeros from publishers predating
    # the fields — tolerant-decode defaults): per-tenant queue/active/
    # parked pressure plus admission and preemption cumulatives (the
    # router merges these fleet-wide for `oimctl tenants`), and the
    # engine's priority-preemption total.  Tenant count is capped at
    # the engine (its row table prunes idle tenants), so this leased
    # value stays bounded however many CNs pass through.
    "tenants": {},
    "qos_preemptions": 0,
    # Live migration drain state (ISSUE 17; False from publishers
    # predating the field — tolerant-decode default): the backend has
    # entered migrate-out drain.  The router stops routing NEW work to
    # it (while /v1/kv + /v1/slot pulls keep flowing), the drain-flip
    # triggers the prefix demote-to-peer sweep, and `oimctl top`
    # shows the DRAIN marker.
    "draining": False,
    "ts": 0.0,
}


def load_key(cn: str) -> str:
    return f"{LOAD_PREFIX}/{cn}"


def parse_load_path(path: str) -> str | None:
    """``load/<cn>`` → cn, else None."""
    parts = path.split("/")
    if len(parts) == 2 and parts[0] == LOAD_PREFIX and parts[1]:
        return parts[1]
    return None


def encode_load(snapshot: dict) -> str:
    out = dict(_DEFAULTS)
    out.update({k: snapshot[k] for k in _DEFAULTS if k in snapshot})
    return json.dumps(out, separators=(",", ":"))


def decode_load(value: str) -> dict[str, Any] | None:
    """Parse a load value; None for malformed/foreign values."""
    try:
        doc = json.loads(value)
    except ValueError:
        return None
    if not isinstance(doc, dict):
        return None
    out = dict(_DEFAULTS)
    for key, default in _DEFAULTS.items():
        val = doc.get(key, default)
        if not isinstance(val, type(default)) and not (
            isinstance(default, float) and isinstance(val, (int, float))
        ):
            return None
        out[key] = val
    return out


class LoadPublisher:
    """Publishes one identity's ``load/<cn>`` key over per-operation
    registry connections (the heartbeat dialing discipline,
    common/regdial.py).  ``cn`` is the publisher's full CommonName —
    what its client cert carries under mTLS, and the one path segment
    the authz grant lets it write."""

    def __init__(
        self,
        cn: str,
        registry_address: str,
        tls=None,
        ttl_seconds: float = 180.0,
    ):
        if not cn or "/" in cn:
            raise ValueError(f"invalid load publisher CN {cn!r}")
        self.cn = cn
        self.registry_address = registry_address
        self.tls = tls
        self.ttl_seconds = ttl_seconds

    def publish(self, snapshot: dict, timeout: float = 5.0) -> None:
        """One leased SetValue of the snapshot.  Single attempt by
        design: the caller is a heartbeat loop that already survives
        (and logs) failures, and a missed load beat just ages the key
        toward its 3-beat lease."""
        from oim_tpu.common.regdial import registry_channel
        from oim_tpu.spec import REGISTRY, oim_pb2

        with registry_channel(self.registry_address, self.tls) as channel:
            REGISTRY.stub(channel).SetValue(
                oim_pb2.SetValueRequest(
                    value=oim_pb2.Value(
                        path=load_key(self.cn),
                        value=encode_load(snapshot),
                    ),
                    ttl_seconds=max(1, int(self.ttl_seconds)),
                ),
                timeout=timeout,
            )

    def withdraw(self, timeout: float = 5.0) -> None:
        """Best-effort immediate delete (graceful shutdown): the
        autoscaler drops this instance from its utilization estimate at
        the watch DELETE instead of at lease expiry."""
        from oim_tpu.common.regdial import registry_channel
        from oim_tpu.spec import REGISTRY, oim_pb2

        try:
            with registry_channel(self.registry_address, self.tls) as channel:
                REGISTRY.stub(channel).SetValue(
                    oim_pb2.SetValueRequest(
                        value=oim_pb2.Value(path=load_key(self.cn), value="")
                    ),
                    timeout=timeout,
                )
        except Exception:
            pass  # the lease expires the key anyway
