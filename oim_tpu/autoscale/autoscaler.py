"""The fleet autoscaler: one registry watch in, slices + replicas out.

Closes the control↔serve loop (ROADMAP item 3): the serving plane
already *publishes* everything a capacity controller needs — discovery
keys (``serve/<id>/address``), live load (``load/serve.<id>``,
autoscale/load.py), eviction marks (``evictions/<vol>``) and chip
health — and the control plane already *offers* idempotent actuation
(ProvisionSlice / MapVolume under the shared retry layer).  This module
is the loop between them, built on the FleetMonitor architecture: ONE
``db.watch`` subscription mirrors all four keyspaces into memory, a
periodic evaluation turns the mirror into a :class:`~.policy.Decision`,
and an actuator/launcher pair applies it.

Crash-safety is registry-mediated, like everything else in this tree:

- Every managed replica has a durable record at
  ``autoscale/replicas/<rid>`` whose ``state`` walks
  ``provisioning → up → draining``; a restarted autoscaler re-drives
  half-done records instead of forgetting them.
- Replica ids are derived from *observed registry state* (lowest free
  index), never from an in-memory counter — so a restart between
  decision and actuation re-picks the same id, and ProvisionSlice's
  name-keyed idempotency makes the re-issued call find the first
  call's slice instead of allocating twice (the chaos-soak acceptance
  in tests/test_autoscale.py).

Replacement is not a band decision: an eviction mark or controller
death on a managed replica's slice, or the DELETE of an up replica's
discovery key (process death → lease expiry), triggers replacement at
the next evaluation regardless of utilization, cooldowns or the
ENOSPC backoff.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from time import monotonic, sleep as _sleep, time as _wall
from typing import Callable

from oim_tpu import log
from oim_tpu.common import locksan
from oim_tpu.autoscale import policy as policy_mod
from oim_tpu.autoscale.actuator import Actuator, PoolExhaustedError
from oim_tpu.autoscale.launcher import Launcher
from oim_tpu.autoscale.load import decode_load, parse_load_path
from oim_tpu.common import events, metrics
from oim_tpu.health import states as health_states

REPLICA_PREFIX = "autoscale/replicas"

PROVISIONING = "provisioning"
UP = "up"
DRAINING = "draining"


def replica_record_key(replica_id: str) -> str:
    return f"{REPLICA_PREFIX}/{replica_id}"


def parse_replica_record_path(path: str) -> str | None:
    parts = path.split("/")
    if len(parts) == 3 and "/".join(parts[:2]) == REPLICA_PREFIX and parts[2]:
        return parts[2]
    return None


@dataclass
class ReplicaRecord:
    """Durable managed-replica state (``autoscale/replicas/<rid>``).
    ``pool`` is the disaggregation pool the replica was scaled out FOR
    (ISSUE 12; empty on single-pool fleets and records written by
    older incarnations — tolerant decode, like the load schema): a
    replacement must restore capacity to the same pool, and the
    per-pool snapshots count booting replicas against their own
    watermarks."""

    replica_id: str
    state: str = PROVISIONING
    chips: int = 1
    controller: str = ""
    placement: dict = field(default_factory=dict)
    ts: float = 0.0
    pool: str = ""

    def encode(self) -> str:
        return json.dumps(
            {
                "state": self.state,
                "chips": self.chips,
                "controller": self.controller,
                "placement": self.placement,
                "ts": self.ts,
                "pool": self.pool,
            },
            separators=(",", ":"),
        )

    @classmethod
    def decode(cls, replica_id: str, value: str) -> "ReplicaRecord | None":
        try:
            doc = json.loads(value)
        except ValueError:
            return None
        if not isinstance(doc, dict) or doc.get("state") not in (
            PROVISIONING,
            UP,
            DRAINING,
        ):
            return None
        return cls(
            replica_id=replica_id,
            state=doc["state"],
            chips=int(doc.get("chips", 1)),
            controller=str(doc.get("controller", "")),
            placement=doc.get("placement") or {},
            ts=float(doc.get("ts", 0.0)),
            pool=str(doc.get("pool", "")),
        )


class Autoscaler:
    """Watch → policy → actuate.  ``start()`` subscribes before the
    snapshot (the WatchValues reconcile discipline), re-drives
    half-done replica records, and runs the evaluation loop on a
    background thread; tests drive :meth:`evaluate_once` directly with
    an injected ``clock`` instead.
    """

    def __init__(
        self,
        db,
        policy: policy_mod.AutoscalePolicy | None,
        actuator: Actuator,
        launcher: Launcher,
        *,
        pool_policies: dict[str, policy_mod.AutoscalePolicy] | None = None,
        replica_prefix: str = "asr-",
        clock: Callable[[], float] = monotonic,
        wall: Callable[[], float] = _wall,
        monitor=None,
        migrate_grace_s: float = 5.0,
    ):
        # ONE policy governs the whole fleet (the pre-disaggregation
        # shape), OR ``pool_policies`` gives each disaggregation pool
        # its own watermarks/cooldowns/bounds (ISSUE 12): prefill and
        # decode replica counts then move independently on their own
        # pools' utilization.  Internally the single-policy fleet IS a
        # pool set with one unnamed pool — one evaluation path, no
        # mode flag threading.
        if pool_policies:
            if policy is not None:
                raise ValueError(
                    "give either policy or pool_policies, not both"
                )
            for pool in pool_policies:
                if not pool or "/" in pool or "-" in pool:
                    raise ValueError(f"invalid pool name {pool!r}")
            self._pool_policies = dict(pool_policies)
        else:
            if policy is None:
                raise ValueError("need a policy (or pool_policies)")
            self._pool_policies = {"": policy}
        self.db = db
        # Legacy accessor + the source of fleet-wide knobs (staleness,
        # default slot capacity for pool-less backends): the single
        # policy, or an arbitrary-but-stable member of the pool set.
        self.policy = policy or next(iter(self._pool_policies.values()))
        self.actuator = actuator
        self.launcher = launcher
        self.replica_prefix = replica_prefix
        self.clock = clock
        self.wall = wall
        # Live migration drain window (ISSUE 17): after POSTing
        # /v1/drain to a victim, how long to wait for its in-flight
        # count to hit zero (slots suspended + shipped to siblings by
        # the router) before tearing it down anyway.  0 = fire the
        # drain and proceed immediately.
        self.migrate_grace_s = migrate_grace_s
        self._states = {
            pool: policy_mod.PolicyState(p)
            for pool, p in self._pool_policies.items()
        }
        self._state = next(iter(self._states.values()))
        # One lock over all mirrors: watch callbacks (registry threads),
        # monitor listeners, and the evaluation thread all touch them.
        # Actuation (RPCs, launcher) ALWAYS runs outside it.  RLock for
        # the FleetMonitor reason: our own db.store calls re-dispatch
        # watch events on this thread.
        self._lock = locksan.new_rlock("Autoscaler._lock")
        self._serve: dict[str, str] = {}  # sid → advertised url
        self._load: dict[str, dict] = {}  # cn → decoded load snapshot
        self._replicas: dict[str, ReplicaRecord] = {}
        # Volume ids with live eviction marks: never reused for a fresh
        # replica (the CSI plane refuses evicted volumes; the mark is
        # the operator's post-mortem record).
        self._evicted_ids: set[str] = set()
        # Backends whose fleet-view gauge series we currently export —
        # a departed backend's series is removed, not left exporting
        # its last pressure forever (the FleetMonitor gauge pattern).
        self._gauged: set[str] = set()
        self._need_replace: dict[str, str] = {}  # rid → reason
        self._cancel_watch: Callable[[], None] | None = None
        self._remove_listener: Callable[[], None] | None = None
        self._monitor = monitor
        self._cond = locksan.new_condition("Autoscaler._cond")
        self._wake = False
        self._stop = False
        self._thread: threading.Thread | None = None
        self._m_desired = metrics.AUTOSCALE_DESIRED
        self._m_actions = metrics.AUTOSCALE_ACTIONS
        self._m_queue = metrics.SERVE_QUEUE_DEPTH
        self._m_active = metrics.SERVE_ACTIVE_SLOTS

    # -- lifecycle ---------------------------------------------------------

    def start(self, run_loop: bool = True) -> "Autoscaler":
        if self._cancel_watch is not None:
            return self
        # Subscribe BEFORE the snapshot so no event between the two is
        # lost; handlers are idempotent so duplicates are harmless.
        self._cancel_watch = self.db.watch("", self._on_event)
        for path, value in self.db.items(""):
            self._on_event(path, value)
        # A record left "up" by a previous incarnation whose discovery
        # key is already gone will get no DELETE event now — mark it
        # for replacement from the snapshot delta.
        with self._lock:
            for rid, record in self._replicas.items():
                if record.state == UP and rid not in self._serve:
                    self._need_replace.setdefault(rid, "missing-after-restart")
        if self._monitor is not None:
            self.attach_monitor(self._monitor)
        if run_loop:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="oim-autoscale-eval"
            )
            self._thread.start()
        return self

    def attach_monitor(self, monitor) -> None:
        """Subscribe to FleetMonitor's classification directly (same
        process) instead of re-deriving it from raw watch events —
        eviction-driven replacement then rides the monitor's grace
        timers and spoof checks for free."""
        if self._remove_listener is not None:
            return
        self._remove_listener = monitor.add_listener(
            on_eviction=self._on_monitor_eviction,
            on_controller_dead=self._on_monitor_controller_dead,
        )

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self._remove_listener is not None:
            self._remove_listener()
            self._remove_listener = None
        if self._cancel_watch is not None:
            self._cancel_watch()
            self._cancel_watch = None

    # -- observation (watch + monitor threads) -----------------------------

    def _on_event(self, path: str, value: str) -> None:
        """Classify one registry mutation; never raises (runs inside
        the DB's watch dispatch — the FleetMonitor rule)."""
        try:
            self._classify(path, value)
        except Exception as exc:
            log.current().error(
                "autoscaler event failed", path=path, error=str(exc)
            )

    def _classify(self, path: str, value: str) -> None:
        parts = path.split("/")
        if len(parts) == 3 and parts[0] == "serve" and parts[2] == "address":
            self._on_serve(parts[1], value)
            return
        cn = parse_load_path(path)
        if cn is not None:
            with self._lock:
                if value == "":
                    self._load.pop(cn, None)
                else:
                    decoded = decode_load(value)
                    if decoded is not None:
                        self._load[cn] = decoded
            return
        volume = health_states.parse_eviction_path(path)
        if volume is not None:
            if value != "":
                with self._lock:
                    self._evicted_ids.add(volume)
                self._on_evicted(volume, "evicted")
            else:
                with self._lock:
                    self._evicted_ids.discard(volume)
            return
        rid = parse_replica_record_path(path)
        if rid is not None:
            with self._lock:
                if value == "":
                    self._replicas.pop(rid, None)
                else:
                    record = ReplicaRecord.decode(rid, value)
                    if record is not None:
                        self._replicas[rid] = record

    def _on_serve(self, sid: str, value: str) -> None:
        wake = False
        with self._lock:
            if value == "":
                self._serve.pop(sid, None)
                record = self._replicas.get(sid)
                if record is not None and record.state == UP:
                    # An up replica's discovery key vanished (process
                    # death → lease expiry, or active withdrawal we did
                    # not initiate): replace it.  Draining replicas lose
                    # their key BY DESIGN (scale-in withdraws first).
                    self._need_replace.setdefault(sid, "discovery-lost")
                    wake = True
            else:
                self._serve[sid] = value.rstrip("/")
        if wake:
            self._notify()

    def _on_evicted(self, volume: str, reason: str) -> None:
        wake = False
        with self._lock:
            record = self._replicas.get(volume)
            if record is not None and record.state != DRAINING:
                # Eviction invalidates the SLICE: relaunching on it
                # would hand the replica dead chips, so the replacement
                # must tear down and re-provision fresh.
                self._need_replace[volume] = reason
                wake = True
        if wake:
            self._notify()

    def _on_monitor_eviction(
        self, volume: str, controller_id: str, reason: str
    ) -> None:
        self._on_evicted(volume, f"evicted:{reason}")

    def _on_monitor_controller_dead(self, controller_id: str) -> None:
        wake = False
        with self._lock:
            for rid, record in self._replicas.items():
                if (
                    record.controller == controller_id
                    and record.state != DRAINING
                ):
                    self._need_replace[rid] = "controller-dead"
                    wake = True
        if wake:
            self._notify()

    def _notify(self) -> None:
        with self._cond:
            self._wake = True
            self._cond.notify()

    # -- evaluation --------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                if not self._wake:
                    self._cond.wait(timeout=min(
                        p.eval_period_s
                        for p in self._pool_policies.values()
                    ))
                if self._stop:
                    return
                self._wake = False
            try:
                self.evaluate_once()
            except Exception as exc:
                # The loop must survive anything an evaluation throws —
                # a dead evaluator is a fleet frozen at its last size.
                log.current().error(
                    "autoscale evaluation failed", error=str(exc)
                )

    def _pool_of_locked(self, sid: str) -> str:
        """Which disaggregation pool a live backend belongs to (lock
        held): the managed record's pool wins (it covers booting
        replicas with no load key yet), then the backend's own load
        snapshot, then "mixed" — the pre-disaggregation default."""
        record = self._replicas.get(sid)
        if record is not None and record.pool:
            return record.pool
        snap = self._load.get(f"serve.{sid}")
        if snap is not None:
            return str(snap.get("pool") or "mixed")
        return "mixed"

    def _live_locked(self, pool: str | None) -> set[str]:
        live = set(self._serve)
        for rid, record in self._replicas.items():
            if record.state in (PROVISIONING, UP):
                live.add(rid)
            elif record.state == DRAINING:
                live.discard(rid)
        if pool:
            live = {
                sid for sid in live if self._pool_of_locked(sid) == pool
            }
        return live

    def fleet_snapshot(
        self,
        pool: str | None = None,
        policy: policy_mod.AutoscalePolicy | None = None,
    ) -> policy_mod.FleetSnapshot:
        """Assemble the policy inputs from the watch mirror.  A backend
        with no (fresh) load key contributes default capacity and zero
        busy — booting replicas dilute utilization, they never spike
        it.  ``pool`` restricts the snapshot to one disaggregation
        pool's members (per-pool watermarks, ISSUE 12); the fleet-view
        gauges update only on the unrestricted call so a per-pool
        evaluation never drops a sibling pool's series."""
        policy = policy or self.policy
        now_wall = self.wall()
        with self._lock:
            live = self._live_locked(pool)
            busy = 0.0
            capacity = 0.0
            gauged: set[str] = set()
            for sid in live:
                snap = self._load.get(f"serve.{sid}")
                if snap is not None and policy.stale_load_s > 0:
                    if now_wall - snap["ts"] > policy.stale_load_s:
                        snap = None
                if snap is None or snap["total_slots"] <= 0:
                    capacity += policy.slots_per_replica
                    continue
                busy += snap["queue_depth"] + snap["active_slots"]
                capacity += snap["total_slots"]
                if pool is None:
                    self._m_queue.set(float(snap["queue_depth"]), sid)
                    self._m_active.set(float(snap["active_slots"]), sid)
                    gauged.add(sid)
            if pool is None:
                # Departed backends stop exporting: a scaled-in
                # replica's last queue depth must not read as live
                # fleet pressure.
                for sid in self._gauged - gauged:
                    self._m_queue.remove(sid)
                    self._m_active.remove(sid)
                self._gauged = gauged
        return policy_mod.FleetSnapshot(
            replicas=len(live), busy=busy, capacity=capacity
        )

    def evaluate_once(self):
        """One full control-loop turn: replacements first (band- and
        cooldown-independent), then re-drive half-done records, then
        the band decision — per POOL when pool policies are configured
        (ISSUE 12: prefill and decode watermarks evaluate against
        their own pools' utilization, hold their own cooldowns, and
        actuate independently).  Returns the band decision (tests
        assert on it); a pooled autoscaler returns {pool: Decision}."""
        self._replace_pending()
        self._redrive_records()
        pooled = "" not in self._pool_policies
        if pooled:
            # Gauge refresh rides the unrestricted snapshot; per-pool
            # snapshots below skip it (a pool view must never drop a
            # sibling pool's series).
            self.fleet_snapshot()
        snapshots = {
            pool: self.fleet_snapshot(pool or None, policy)
            for pool, policy in self._pool_policies.items()
        }
        # ONE band-decision path for single- and multi-pool fleets:
        # policy.decide_pools is what runs here, not a parallel
        # implementation beside it.
        band = policy_mod.decide_pools(self._pool_policies, snapshots)
        decisions: dict[str, policy_mod.Decision] = {}
        desired_total = 0
        for pool, policy in self._pool_policies.items():
            decisions[pool], desired = self._evaluate_pool(
                pool, policy, snapshots[pool], band[pool]
            )
            desired_total += desired
        self._m_desired.set(float(desired_total))
        return decisions if pooled else decisions[""]

    def _evaluate_pool(
        self,
        pool: str,
        policy: policy_mod.AutoscalePolicy,
        snapshot: policy_mod.FleetSnapshot,
        decision: policy_mod.Decision,
    ) -> tuple[policy_mod.Decision, int]:
        """Gate + actuate one pool's band decision; returns (decision,
        the replica count this evaluation wants the pool at — the
        fleet desired gauge's summand)."""
        state = self._states[pool]
        now = self.clock()
        desired = snapshot.replicas
        held = ""
        if decision.direction == policy_mod.SCALE_OUT:
            desired = snapshot.replicas + decision.count
            if state.enospc_blocks(now):
                held = "enospc_backoff"
                log.current().debug("scale-out held: ENOSPC backoff")
            elif state.cooldown_blocks(policy_mod.SCALE_OUT, now):
                held = "cooldown"
                log.current().debug("scale-out held: cooldown")
        elif decision.direction == policy_mod.SCALE_IN:
            desired = snapshot.replicas - decision.count
            if state.cooldown_blocks(policy_mod.SCALE_IN, now):
                held = "cooldown"
                log.current().debug("scale-in held: cooldown")
        if decision.direction is not None:
            # Decision journal (ISSUE 9): every evaluation that wants
            # to act — whether it proceeds, is held by a cooldown/
            # backoff gate, or ends clamped inside the action — leaves
            # one flight-recorder row carrying the snapshot it decided
            # on, so "why did (or didn't) it scale?" is answerable from
            # `oimctl events --kind autoscale` alone.
            events.emit(
                "autoscale.decision",
                component="oim-autoscale",
                direction=decision.direction,
                count=decision.count,
                reason=decision.reason,
                utilization=round(decision.utilization, 3),
                busy=round(snapshot.busy, 2),
                capacity=round(snapshot.capacity, 2),
                replicas=snapshot.replicas,
                high_watermark=policy.high_watermark,
                low_watermark=policy.low_watermark,
                pool=pool,
                held=held,
            )
        if not held:
            if decision.direction == policy_mod.SCALE_OUT:
                self._scale_out(decision, pool, policy, state)
            elif decision.direction == policy_mod.SCALE_IN:
                self._scale_in(decision, pool, state)
        return decision, desired

    # -- actuation helpers (never called under self._lock) ------------------

    def _store_record(self, record: ReplicaRecord) -> None:
        record.ts = self.wall()
        with self._lock:
            self._replicas[record.replica_id] = record
        self.db.store(
            replica_record_key(record.replica_id), record.encode()
        )

    def _drop_record(self, replica_id: str) -> None:
        with self._lock:
            self._replicas.pop(replica_id, None)
            self._need_replace.pop(replica_id, None)
        self.db.store(replica_record_key(replica_id), "")

    def _state_for(self, pool: str) -> policy_mod.PolicyState:
        """The cooldown/backoff state a record's pool evaluates under
        (replacement/re-drive paths — a record whose pool has no
        configured policy, e.g. after a reconfiguration, degrades to
        an arbitrary-but-stable state rather than crashing)."""
        return self._states.get(pool, self._state)

    def _next_replica_id(self, pool: str = "") -> str:
        """Lowest free index over BOTH the replica records and the
        discovery table — derived from observed registry state so a
        restarted autoscaler re-picks the id a crashed incarnation was
        about to provision (ProvisionSlice then finds the existing
        slice: exactly one allocation).  Pooled replicas carry their
        pool in the id (``asr-prefill-0``) so an operator reading
        `oimctl top` sees the partition at a glance."""
        prefix = (
            f"{self.replica_prefix}{pool}-" if pool
            else self.replica_prefix
        )
        with self._lock:
            taken = set(self._replicas) | set(self._serve) | self._evicted_ids
        k = 0
        while f"{prefix}{k}" in taken:
            k += 1
        return f"{prefix}{k}"

    def _provision_and_launch(self, record: ReplicaRecord) -> bool:
        """Drive one replica from its record to UP; returns False on
        pool exhaustion (the caller clamps + backs off)."""
        rid = record.replica_id
        placement = self.actuator.provision(rid, record.chips)
        record.controller = placement.get("controller", record.controller)
        record.placement = placement
        self._launch(record)
        record.state = UP
        self._store_record(record)
        return True

    def _launch(self, record: ReplicaRecord) -> None:
        """One launcher hand-off: the pool rides INTO the launcher
        beside the placement (the SubprocessLauncher template turns it
        into --pool; the record, not the placement, is its durable
        home) — shared by provision-and-launch AND relaunch so a
        replacement can never strip the replica's pool."""
        self.launcher.launch(
            record.replica_id,
            dict(record.placement, pool=record.pool) if record.pool
            else record.placement,
        )

    def _scale_out(
        self,
        decision: policy_mod.Decision,
        pool: str = "",
        policy: policy_mod.AutoscalePolicy | None = None,
        state: policy_mod.PolicyState | None = None,
    ) -> None:
        policy = policy or self.policy
        state = state or self._state
        launched = 0
        for _ in range(decision.count):
            rid = self._next_replica_id(pool)
            record = ReplicaRecord(
                replica_id=rid,
                state=PROVISIONING,
                chips=policy.chips_per_replica,
                pool=pool,
            )
            self._store_record(record)
            try:
                self._provision_and_launch(record)
            except PoolExhaustedError as exc:
                self._clamped(rid, decision, str(exc), policy, state)
                self._drop_record(rid)
                return
            except Exception as exc:
                # Transient actuation failure: the PROVISIONING record
                # stays and the next evaluation re-drives it (all the
                # RPCs behind it are idempotent).
                self._m_actions.inc(policy_mod.SCALE_OUT, "failed")
                log.current().warning(
                    "scale-out actuation failed; will re-drive",
                    replica=rid,
                    error=str(exc),
                )
                return
            launched += 1
            self._m_actions.inc(policy_mod.SCALE_OUT, "ok")
            events.emit(
                "autoscale.scale_out",
                component="oim-autoscale",
                subject=rid,
                utilization=round(decision.utilization, 3),
                reason=decision.reason,
                pool=pool,
            )
            log.current().info(
                "scaled out", replica=rid, reason=decision.reason
            )
        if launched:
            state.note_action(policy_mod.SCALE_OUT, self.clock())

    def _clamped(
        self,
        rid: str,
        decision: policy_mod.Decision,
        error: str,
        policy: policy_mod.AutoscalePolicy | None = None,
        state: policy_mod.PolicyState | None = None,
    ) -> None:
        """ENOSPC: clamp desire to what the pool holds and back off —
        a full pool is re-probed after enospc_backoff_s, not hammered
        every evaluation (and never crash-looped on)."""
        policy = policy or self.policy
        (state or self._state).note_enospc(self.clock())
        self._m_actions.inc(policy_mod.SCALE_OUT, "clamped")
        events.emit(
            "autoscale.clamped",
            component="oim-autoscale",
            severity=events.WARNING,
            subject=rid,
            utilization=round(decision.utilization, 3),
            backoff_s=policy.enospc_backoff_s,
            error=error,
        )
        log.current().warning(
            "scale-out clamped: chip pool exhausted",
            replica=rid,
            backoff_s=policy.enospc_backoff_s,
            error=error,
        )

    def _least_loaded(
        self, count: int, pool: str = ""
    ) -> list[ReplicaRecord]:
        with self._lock:
            candidates = [
                r
                for r in self._replicas.values()
                if r.state == UP and r.replica_id not in self._need_replace
                and (not pool or r.pool == pool)
            ]
            loads = {
                r.replica_id: self._load.get(f"serve.{r.replica_id}")
                for r in candidates
            }
        def busy(record: ReplicaRecord) -> float:
            snap = loads.get(record.replica_id)
            if snap is None:
                return 0.0
            return float(snap["queue_depth"] + snap["active_slots"])

        candidates.sort(key=lambda r: (busy(r), r.replica_id))
        return candidates[:count]

    def _scale_in(
        self,
        decision: policy_mod.Decision,
        pool: str = "",
        state: policy_mod.PolicyState | None = None,
    ) -> None:
        state = state or self._state
        victims = self._least_loaded(decision.count, pool)
        if not victims:
            log.current().info(
                "scale-in wanted but no managed replica to remove "
                "(static backends are never scaled in)"
            )
            return
        removed = 0
        for record in victims:
            try:
                self._retire(record)
            except Exception as exc:
                # Keep the DRAINING record: the next evaluation's
                # re-drive finishes the teardown (idempotent hops).
                self._m_actions.inc(policy_mod.SCALE_IN, "failed")
                log.current().warning(
                    "scale-in teardown failed; will re-drive",
                    replica=record.replica_id,
                    error=str(exc),
                )
                continue
            removed += 1
            self._m_actions.inc(policy_mod.SCALE_IN, "ok")
            events.emit(
                "autoscale.scale_in",
                component="oim-autoscale",
                subject=record.replica_id,
                utilization=round(decision.utilization, 3),
                reason=decision.reason,
                pool=pool,
            )
            log.current().info(
                "scaled in", replica=record.replica_id, reason=decision.reason
            )
        if removed:
            state.note_action(policy_mod.SCALE_IN, self.clock())

    def _retire(self, record: ReplicaRecord) -> None:
        """The scale-in drain sequence (doc/serving.md): (1) mark the
        record DRAINING so the discovery DELETE below is not read as a
        death, (2) withdraw the discovery key — routers stop sending
        within one watch event, (3) MIGRATE OUT (ISSUE 17): POST
        /v1/drain so the victim suspends its in-flight slots for the
        router to ship to siblings, and wait up to ``migrate_grace_s``
        for its in-flight count to reach zero, (4) drain + stop the
        process, (5) unmap + delete the slice, (6) drop the record.
        Step 3 is best-effort by construction — it must never raise
        (``_scale_in`` keeps DRAINING records for re-drive on
        exception, and a teardown must not wedge on an unreachable
        victim)."""
        rid = record.replica_id
        record.state = DRAINING
        self._store_record(record)
        # Capture the advertised url BEFORE withdrawing the discovery
        # key: the withdraw round-trips through our own registry watch
        # (``_on_serve`` pops ``self._serve[rid]``), so a lookup after
        # the store would always come up empty and silently skip the
        # migrate-out step.
        with self._lock:
            url = self._serve.get(rid, "")
        self.db.store(f"serve/{rid}/address", "")
        self._migrate_out(rid, url=url)
        self.launcher.stop(rid, drain=True)
        # Withdraw AGAIN after the stop: the victim's own heartbeat may
        # have re-published the key in the window between the first
        # withdraw and its SIGTERM handler (oim-serve's graceful path
        # deregisters itself, but a launcher without that courtesy — or
        # a beat racing the signal — must not leave a zombie key to age
        # out on its lease).  Idempotent: deleting an absent key is a
        # no-op.
        self.db.store(f"serve/{rid}/address", "")
        if record.controller:
            self.actuator.deprovision(rid, record.controller)
        self._drop_record(rid)

    def _migrate_out(self, rid: str, url: str | None = None) -> None:
        """Best-effort live-migration kick for one victim (ISSUE 17):
        POST its ``/v1/drain`` (the serve endpoint is idempotent and
        replies the current in-flight count), then poll the same
        endpoint until in-flight hits zero — every suspended slot
        shipped to a sibling by the router — or ``migrate_grace_s``
        expires.  Swallows EVERYTHING: an unreachable, mTLS-guarded,
        or pre-migration victim degrades to the old wait-for-drain
        teardown, never to a wedged autoscaler.  ``url`` lets a caller
        that already withdrew the victim's discovery key (``_retire``)
        pass the address it captured first."""
        if url is None:
            with self._lock:
                url = self._serve.get(rid, "")
        url = url.rstrip("/")
        if not url:
            return
        import urllib.request

        def drain_once() -> int | None:
            req = urllib.request.Request(
                url + "/v1/drain", data=b"{}",
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                reply = json.loads(resp.read())
            return int(reply.get("in_flight", 0))
        try:
            in_flight = drain_once()
        except Exception as exc:
            log.current().info(
                "migrate-out drain unreachable; plain teardown",
                replica=rid, error=f"{type(exc).__name__}: {exc}",
            )
            return
        events.emit(
            "autoscale.migrate_out",
            component="oim-autoscale",
            subject=rid,
            in_flight=in_flight,
        )
        deadline = self.clock() + max(0.0, self.migrate_grace_s)
        while in_flight and self.clock() < deadline:
            _sleep(0.05)
            try:
                in_flight = drain_once()
            except Exception:
                return  # victim already gone; teardown proceeds
        if in_flight:
            log.current().warning(
                "migrate-out grace expired with work in flight",
                replica=rid, in_flight=in_flight,
            )

    def _redrive_records(self) -> None:
        """Finish what a crashed (or transiently failed) incarnation
        started: PROVISIONING records re-run the provision+launch path,
        DRAINING records re-run the teardown — both end-to-end
        idempotent."""
        with self._lock:
            pending = [
                ReplicaRecord(**vars(r))
                for r in self._replicas.values()
                if r.state in (PROVISIONING, DRAINING)
            ]
        for record in pending:
            try:
                if record.state == PROVISIONING:
                    self._provision_and_launch(record)
                else:
                    self._retire(record)
            except PoolExhaustedError as exc:
                self._state_for(record.pool).note_enospc(self.clock())
                log.current().warning(
                    "re-drive held: chip pool exhausted",
                    replica=record.replica_id,
                    error=str(exc),
                )
            except Exception as exc:
                log.current().warning(
                    "replica re-drive failed; will retry",
                    replica=record.replica_id,
                    state=record.state,
                    error=str(exc),
                )

    def _replace_pending(self) -> None:
        """Replace dead/evicted replicas — independent of the band,
        cooldowns and the ENOSPC backoff (capacity the fleet already
        had is restored, not grown)."""
        with self._lock:
            pending = {
                rid: reason
                for rid, reason in self._need_replace.items()
                if rid in self._replicas
            }
            # Entries whose record vanished (raced teardown) are stale.
            for rid in list(self._need_replace):
                if rid not in pending:
                    del self._need_replace[rid]
        for rid, reason in pending.items():
            with self._lock:
                record = self._replicas.get(rid)
            if record is None:
                continue
            try:
                if reason.startswith("evicted") or reason == "controller-dead":
                    self._replace_on_fresh_slice(record, reason)
                else:
                    self._relaunch(record, reason)
            except PoolExhaustedError as exc:
                self._state_for(record.pool).note_enospc(self.clock())
                self._m_actions.inc("replace", "clamped")
                log.current().warning(
                    "replacement held: chip pool exhausted",
                    replica=rid,
                    error=str(exc),
                )
            except Exception as exc:
                self._m_actions.inc("replace", "failed")
                log.current().warning(
                    "replacement failed; will retry",
                    replica=rid,
                    reason=reason,
                    error=str(exc),
                )

    def _relaunch(self, record: ReplicaRecord, reason: str) -> None:
        """The process died but its slice is healthy: restart on the
        recorded placement (no control-plane round trip at all)."""
        rid = record.replica_id
        self.launcher.stop(rid, drain=False)  # clear any remnant
        self._launch(record)
        with self._lock:
            self._need_replace.pop(rid, None)
        self._m_actions.inc("replace", "ok")
        events.emit(
            "autoscale.replace",
            component="oim-autoscale",
            severity=events.WARNING,
            subject=rid,
            reason=reason,
            fresh_slice=False,
        )
        log.current().warning("replica relaunched", replica=rid, reason=reason)

    def _replace_on_fresh_slice(
        self, record: ReplicaRecord, reason: str
    ) -> None:
        """The slice itself is bad (chip failure / dead controller):
        tear the old replica down best-effort and bring capacity back
        on a NEW replica id — the evicted volume id stays retired (the
        CSI plane refuses evicted volumes by design, and the eviction
        mark remains for the operator's post-mortem)."""
        rid = record.replica_id
        with self._lock:
            # Retire the id even WITHOUT an eviction mark (controller
            # death leaves none): a dead controller may still hold an
            # allocation under this name, and re-using it would alias
            # two slices to one volume id when the controller recovers.
            self._evicted_ids.add(rid)
        # Eviction/controller-death replacement (ISSUE 17): when the
        # victim's daemon is still reachable (the SLICE is doomed, the
        # process often is not yet), migrate its in-flight slots out
        # before the teardown destroys them.  Best-effort — a dead
        # process just skips this.
        self._migrate_out(rid)
        self.launcher.stop(rid, drain=False)
        if record.controller:
            try:
                self.actuator.deprovision(rid, record.controller)
            except Exception as exc:
                # A dead controller cannot tear down its own slice; the
                # eviction mark + operator remap own that cleanup.
                log.current().warning(
                    "deprovision of evicted replica failed",
                    replica=rid,
                    controller=record.controller,
                    error=str(exc),
                )
        self._drop_record(rid)
        fresh = ReplicaRecord(
            replica_id=self._next_replica_id(record.pool),
            state=PROVISIONING,
            chips=record.chips or self.policy.chips_per_replica,
            pool=record.pool,
        )
        self._store_record(fresh)
        self._provision_and_launch(fresh)
        self._m_actions.inc("replace", "ok")
        events.emit(
            "autoscale.replace",
            component="oim-autoscale",
            severity=events.WARNING,
            subject=rid,
            replacement=fresh.replica_id,
            reason=reason,
            fresh_slice=True,
        )
        log.current().warning(
            "replica replaced on a fresh slice",
            replica=rid,
            replacement=fresh.replica_id,
            reason=reason,
        )

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "backends": dict(self._serve),
                "replicas": {
                    rid: {
                        "state": r.state,
                        "chips": r.chips,
                        "controller": r.controller,
                        "pool": r.pool,
                    }
                    for rid, r in self._replicas.items()
                },
                "pending_replacements": dict(self._need_replace),
                "load": {cn: dict(s) for cn, s in self._load.items()},
            }
