"""Fleet autoscaler: traffic-driven scale-out/in for the serving plane.

Closes the control↔serve loop (ROADMAP item 3, ISSUE 8): serve
backends publish live load beside their discovery heartbeat
(:mod:`~oim_tpu.autoscale.load`), a policy engine turns the fleet's
utilization into replica-count decisions
(:mod:`~oim_tpu.autoscale.policy`), and the autoscaler actuates them
through the same idempotent control-plane RPCs the CSI plane uses
(:mod:`~oim_tpu.autoscale.actuator`) plus a pluggable process launcher
(:mod:`~oim_tpu.autoscale.launcher`).  The daemon entry point is
``oim-autoscale`` (oim_tpu/cli/autoscale_main.py).
"""

from oim_tpu.autoscale.actuator import (
    Actuator,
    ControllerActuator,
    PoolExhaustedError,
)
from oim_tpu.autoscale.autoscaler import (
    Autoscaler,
    ReplicaRecord,
    parse_replica_record_path,
    replica_record_key,
)
from oim_tpu.autoscale.launcher import (
    InProcessLauncher,
    Launcher,
    SubprocessLauncher,
)
from oim_tpu.autoscale.load import (
    LoadPublisher,
    decode_load,
    encode_load,
    load_key,
    parse_load_path,
)
from oim_tpu.autoscale.policy import (
    SCALE_IN,
    SCALE_OUT,
    AutoscalePolicy,
    Decision,
    FleetSnapshot,
    PolicyState,
    decide,
    decide_pools,
)

__all__ = [
    "Actuator",
    "ControllerActuator",
    "PoolExhaustedError",
    "Autoscaler",
    "ReplicaRecord",
    "replica_record_key",
    "parse_replica_record_path",
    "Launcher",
    "InProcessLauncher",
    "SubprocessLauncher",
    "LoadPublisher",
    "load_key",
    "parse_load_path",
    "encode_load",
    "decode_load",
    "AutoscalePolicy",
    "FleetSnapshot",
    "Decision",
    "PolicyState",
    "decide",
    "decide_pools",
    "SCALE_OUT",
    "SCALE_IN",
]
