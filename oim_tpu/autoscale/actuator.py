"""Device-plane actuation for the autoscaler: slices in, slices out.

A replica's accelerator footprint is one *provisioned* allocation named
after the replica id, provisioned and mapped through the same
registry-proxied controller RPCs the CSI plane uses — and therefore
with the same guarantees the autoscaler's crash-safety leans on:

- ``ProvisionSlice`` is idempotent by name (controller.py): an
  autoscaler that crashed between decision and actuation re-derives the
  same replica id from registry state on restart and re-issues the
  call; the second provision finds the first's allocation instead of
  allocating twice.
- ``MapVolume`` is volume_id-keyed idempotent behind the controller's
  placement cache (PR 2), so a retried map returns the original chips.
- Every hop runs under the shared retry policy + breaker
  (``csi.backend.RemoteBackend`` carries both), so 20% injected
  transport failure costs retries, not leaked slices — the chaos soak
  in tests/test_autoscale.py pins this end-to-end.

``ENOSPC`` from the chip pool surfaces as :class:`PoolExhaustedError`
after every candidate controller declined; the policy layer answers
with clamp + backoff, never a crash-loop (ISSUE 8).
"""

from __future__ import annotations

import threading
from typing import Protocol

import grpc

from oim_tpu import log
from oim_tpu.common import resilience


class PoolExhaustedError(RuntimeError):
    """No candidate controller could place the slice (chip pool full)."""


class Actuator(Protocol):
    def provision(self, replica_id: str, chip_count: int) -> dict:
        """Provision + map a slice named ``replica_id``; returns the
        placement (tpu-bootstrap-shaped dict, with the chosen
        controller id under ``controller``).  Raises
        :class:`PoolExhaustedError` when the pool cannot hold it."""
        ...

    def deprovision(self, replica_id: str, controller_id: str) -> None:
        """Unmap and delete the replica's slice; idempotent."""
        ...

    def close(self) -> None: ...


class ControllerActuator:
    """Drives real controllers through the registry proxy.

    One ``RemoteBackend`` per candidate controller (lazily dialed,
    cached — each carries its own breaker so one dead controller fails
    fast while the others stay usable).  Scale-out walks the candidate
    list in order and takes the first placement; RESOURCE_EXHAUSTED
    (the chip pool's ENOSPC) moves to the next candidate, any other
    error propagates (the caller's retry/backoff owns it).
    """

    def __init__(
        self,
        registry_address: str,
        controller_ids: list[str],
        tls_loader=None,
        retry: resilience.RetryPolicy | None = None,
    ):
        if not controller_ids:
            raise ValueError("need at least one candidate controller id")
        self.registry_address = registry_address
        self.controller_ids = list(controller_ids)
        self.tls_loader = tls_loader
        self.retry = retry
        self._lock = threading.Lock()
        self._backends: dict[str, object] = {}

    def _backend(self, controller_id: str):
        from oim_tpu.csi.backend import RemoteBackend

        with self._lock:
            backend = self._backends.get(controller_id)
            if backend is None:
                backend = RemoteBackend(
                    self.registry_address,
                    controller_id,
                    tls_loader=self.tls_loader,
                    retry=self.retry,
                )
                self._backends[controller_id] = backend
        return backend

    def provision(self, replica_id: str, chip_count: int) -> dict:
        from oim_tpu.csi.backend import VolumeError

        last_enospc: VolumeError | None = None
        for cid in self.controller_ids:
            backend = self._backend(cid)
            try:
                backend.provision(replica_id, chip_count)
                # Provisioned-mode map: attach the allocation just
                # created (idempotent re-attach on retry/restart).
                staged = backend.create_device(replica_id, {})
            except VolumeError as exc:
                if exc.code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    log.current().info(
                        "controller pool full; trying next candidate",
                        replica=replica_id,
                        controller=cid,
                    )
                    last_enospc = exc
                    continue
                raise
            placement = staged.bootstrap()
            placement["controller"] = cid
            return placement
        raise PoolExhaustedError(
            f"no controller could place {chip_count} chips for "
            f"{replica_id!r}: {last_enospc}"
        )

    def deprovision(self, replica_id: str, controller_id: str) -> None:
        from oim_tpu.csi.backend import VolumeError

        backend = self._backend(controller_id)
        try:
            backend.destroy_device(replica_id)
        except VolumeError as exc:
            # NOT_FOUND = already gone (a retried teardown); anything
            # else must surface so the replica record is kept and the
            # next evaluation retries the teardown.
            if exc.code != grpc.StatusCode.NOT_FOUND:
                raise
        try:
            backend.delete(replica_id)
        except VolumeError as exc:
            if exc.code != grpc.StatusCode.NOT_FOUND:
                raise

    def close(self) -> None:
        with self._lock:
            backends = list(self._backends.values())
            self._backends.clear()
        for backend in backends:
            backend.close()
