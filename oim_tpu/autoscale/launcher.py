"""Replica bring-up/teardown seam for the autoscaler.

The actuator (actuator.py) owns the *device* side of a replica — the
provisioned slice and its mapping; a :class:`Launcher` owns the
*process* side — starting an oim-serve instance on that placement and
stopping it again.  Keeping the seam this narrow is what makes the
simulation harness deterministic: tests plug a fake that flips registry
keys, deployments plug :class:`SubprocessLauncher` which execs the real
binary, and embedders plug :class:`InProcessLauncher` with a factory.

The launcher does NOT register the replica: a launched backend
announces itself (`oim-serve --serve-id`), exactly like an
operator-started one — the autoscaler observes its arrival through the
same ``serve/`` watch as the router, so a replica's lifecycle looks
identical regardless of who started it.

``stop(drain=True)`` is the scale-in path: the launcher must let
in-flight requests finish (SIGTERM → oim-serve's graceful drain; an
in-process server's ``engine.drain()`` + bounded wait).  ``drain=False``
is the replacement path for a replica already presumed dead.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time
from typing import Callable, Protocol

from oim_tpu import log


class Launcher(Protocol):
    def launch(self, replica_id: str, placement: dict) -> None:
        """Bring up a serving backend for ``replica_id`` on
        ``placement`` (a tpu-bootstrap-shaped dict from the actuator).
        Idempotent per id: launching an id that is already up restarts
        it."""
        ...

    def stop(self, replica_id: str, drain: bool = True) -> None:
        """Tear the backend down; idempotent (unknown ids no-op)."""
        ...

    def close(self) -> None:
        """Stop everything this launcher started (daemon shutdown)."""
        ...


class InProcessLauncher:
    """Factory-driven launcher for tests, demos and embedders: the
    factory returns a handle; ``stop`` calls ``handle.stop()`` (and
    ``handle.drain()`` first when asked and available)."""

    def __init__(self, factory: Callable[[str, dict], object]):
        self._factory = factory
        self._lock = threading.Lock()
        self._handles: dict[str, object] = {}

    def launch(self, replica_id: str, placement: dict) -> None:
        self.stop(replica_id, drain=False)
        handle = self._factory(replica_id, placement)
        with self._lock:
            self._handles[replica_id] = handle

    def stop(self, replica_id: str, drain: bool = True) -> None:
        with self._lock:
            handle = self._handles.pop(replica_id, None)
        if handle is None:
            return
        if drain and hasattr(handle, "drain"):
            try:
                handle.drain()
            except Exception as exc:
                log.current().warning(
                    "replica drain failed", replica=replica_id, error=str(exc)
                )
        if hasattr(handle, "stop"):
            handle.stop()

    def close(self) -> None:
        with self._lock:
            ids = list(self._handles)
        for rid in ids:
            self.stop(rid, drain=False)


class SubprocessLauncher:
    """Launches each replica as an oim-serve subprocess.

    ``argv_template`` is the command line with ``{id}`` substituted per
    replica (e.g. ``["python", "-m", "oim_tpu.cli.serve_main",
    "--serve-id", "{id}", "--registry-address", "tcp://...", ...]``).
    The placement is written to ``<state_dir>/<id>/tpu-bootstrap.json``
    and exported as ``TPU_BOOTSTRAP`` — the same chip-binding contract
    the CSI plane hands pods (doc/compute.md).

    ``stop(drain=True)`` sends SIGTERM and waits ``drain_timeout_s``
    (oim-serve's own --drain-timeout bounds the inner wait), then
    escalates to SIGKILL — a wedged replica must not wedge the
    autoscaler's scale-in.
    """

    def __init__(
        self,
        argv_template: list[str],
        state_dir: str,
        env: dict | None = None,
        drain_timeout_s: float = 150.0,
    ):
        if not argv_template:
            raise ValueError("argv_template must not be empty")
        self.argv_template = list(argv_template)
        self.state_dir = state_dir
        self.env = dict(env) if env else {}
        self.drain_timeout_s = drain_timeout_s
        self._lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}

    def _argv(self, replica_id: str, pool: str = "") -> list[str]:
        """The replica's command line: ``{id}``/``{pool}`` substituted
        from the template, and — when the autoscaler hands a pool role
        down (per-pool policies, ISSUE 12) and the template claims it
        nowhere — ``--pool <role>`` appended, so a pooled scale-out
        launches a replica that actually REGISTERS in its pool (the
        router partitions on what the replica itself reports, not on
        what the autoscaler intended)."""
        argv = [
            arg.format(id=replica_id, pool=pool or "mixed")
            for arg in self.argv_template
        ]
        if pool and "--pool" not in self.argv_template and not any(
            "{pool}" in arg for arg in self.argv_template
        ):
            argv += ["--pool", pool]
        return argv

    def _pidfile(self, replica_id: str) -> str:
        return os.path.join(self.state_dir, replica_id, "pid")

    def launch(self, replica_id: str, placement: dict) -> None:
        self.stop(replica_id, drain=False)
        replica_dir = os.path.join(self.state_dir, replica_id)
        os.makedirs(replica_dir, exist_ok=True)
        bootstrap = os.path.join(replica_dir, "tpu-bootstrap.json")
        # The pool role rides IN the placement dict from the autoscaler
        # (Launcher's two-arg seam predates it) but is not a
        # chip-binding field: it reaches the process as --pool, not
        # through the bootstrap.
        placement = dict(placement)
        pool = str(placement.pop("pool", "") or "")
        with open(bootstrap, "w") as fh:
            json.dump(placement, fh)
        env = dict(os.environ)
        env.update(self.env)
        env["TPU_BOOTSTRAP"] = bootstrap
        proc = subprocess.Popen(self._argv(replica_id, pool), env=env)
        with self._lock:
            self._procs[replica_id] = proc
        # Durable pid: replicas deliberately OUTLIVE the autoscaler
        # daemon (its shutdown must not be a fleet outage), so a
        # RESTARTED daemon holds no Popen handle for them — the pidfile
        # is how its scale-in still reaches the orphaned process.
        with open(self._pidfile(replica_id), "w") as fh:
            fh.write(str(proc.pid))
        log.current().info(
            "replica launched", replica=replica_id, pid=proc.pid
        )

    def _orphan_pid(self, replica_id: str) -> int | None:
        try:
            with open(self._pidfile(replica_id)) as fh:
                pid = int(fh.read().strip())
        except (OSError, ValueError):
            return None
        try:
            os.kill(pid, 0)
        except (ProcessLookupError, PermissionError):
            return None
        return pid

    def _drop_pidfile(self, replica_id: str) -> None:
        try:
            os.unlink(self._pidfile(replica_id))
        except OSError:
            pass

    def stop(self, replica_id: str, drain: bool = True) -> None:
        with self._lock:
            proc = self._procs.pop(replica_id, None)
        if proc is None or proc.poll() is not None:
            self._stop_orphan(replica_id, drain)
            return
        try:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=self.drain_timeout_s if drain else 5.0)
        except subprocess.TimeoutExpired:
            log.current().warning(
                "replica did not exit on SIGTERM; killing",
                replica=replica_id,
                pid=proc.pid,
            )
            proc.kill()
            proc.wait(timeout=10.0)
        except ProcessLookupError:
            pass
        self._drop_pidfile(replica_id)
        log.current().info("replica stopped", replica=replica_id)

    def _stop_orphan(self, replica_id: str, drain: bool) -> None:
        """Stop a replica launched by a PREVIOUS daemon incarnation
        (known only through its pidfile)."""
        pid = self._orphan_pid(replica_id)
        if pid is None:
            self._drop_pidfile(replica_id)
            return
        try:
            os.kill(pid, signal.SIGTERM)
            deadline = time.monotonic() + (
                self.drain_timeout_s if drain else 5.0
            )
            while time.monotonic() < deadline:
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    break
                time.sleep(0.2)
            else:
                log.current().warning(
                    "orphan replica did not exit on SIGTERM; killing",
                    replica=replica_id,
                    pid=pid,
                )
                os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        self._drop_pidfile(replica_id)
        log.current().info(
            "orphan replica stopped", replica=replica_id, pid=pid
        )

    def close(self) -> None:
        """Release handles WITHOUT stopping the replicas: a graceful
        autoscaler shutdown must not be a fleet outage.  The replicas
        keep serving; the restarted daemon converges from the durable
        records and reaches them through their pidfiles.  Stopping the
        fleet is scale-in's job (or the operator's, explicitly)."""
        with self._lock:
            self._procs.clear()
