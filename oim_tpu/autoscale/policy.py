"""Autoscaling policy: utilization band → replica-count decisions.

Pure decision logic, deliberately separated from the watch/actuation
machinery (autoscaler.py) the way EvictionPolicy is separate from the
FleetMonitor: every boundary condition here — watermark edges, the
anti-flap projection, min/max clamps, step bounds, cooldown expiry —
is a unit-testable function of explicit inputs, never of wall time.

The control law:

- **Utilization** is fleet busy work over fleet slot capacity, where
  busy counts queued requests as well as decoding slots (a deep queue
  on a full fleet must read as >1.0, not saturate at 1.0).
- **Band with hysteresis**: scale OUT only when utilization exceeds
  ``high_watermark`` *strictly*; scale IN only when it is *strictly*
  below ``low_watermark``.  Load sitting exactly on a watermark takes
  no action — the flap tests pin this.
- **Anti-flap projection**: a scale-in is only allowed if the fleet's
  utilization *after* removing the replicas stays strictly below the
  high watermark; otherwise the very next evaluation would scale back
  out.  Under load oscillating at the band edge this is what makes
  ramp-down converge instead of ringing.
- **Cooldowns** are per-direction and live in :class:`PolicyState`
  (the only time-dependent piece, fed an explicit ``now`` from the
  autoscaler's injectable clock).  An action is allowed again once
  ``now - last >= cooldown`` — the expiry instant itself is allowed.
- **ENOSPC backoff**: a chip-pool-exhausted scale-out clamps desire
  and blocks further scale-OUT attempts for ``enospc_backoff_s`` so a
  full pool is probed, not hammered (the circuit-breaker stance
  applied to capacity).  Scale-in and replacement stay allowed.

Replacement of a dead/evicted replica is *not* a band decision and
does not pass through here: the autoscaler replaces unconditionally,
ignoring band and cooldowns (ISSUE 8 tentpole).
"""

from __future__ import annotations

from dataclasses import dataclass

SCALE_OUT = "out"
SCALE_IN = "in"


@dataclass(frozen=True)
class AutoscalePolicy:
    """Knobs for the control loop (doc/operations.md "Autoscaling")."""

    min_replicas: int = 1
    max_replicas: int = 4
    chips_per_replica: int = 1
    slots_per_replica: int = 8
    high_watermark: float = 0.8
    low_watermark: float = 0.3
    max_step: int = 1
    scale_out_cooldown_s: float = 30.0
    scale_in_cooldown_s: float = 120.0
    eval_period_s: float = 10.0
    enospc_backoff_s: float = 60.0
    # A load key older than this (by its own ts field vs the caller's
    # wall clock) is treated as absent: capacity still counts, busy
    # does not — a wedged backend must not pin utilization high
    # forever.  0 disables staleness checks (deterministic sims).
    stale_load_s: float = 0.0

    def __post_init__(self) -> None:
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}, {self.max_replicas}"
            )
        if not 0.0 < self.low_watermark < self.high_watermark:
            raise ValueError(
                f"need 0 < low_watermark < high_watermark, got "
                f"{self.low_watermark}, {self.high_watermark}"
            )
        if self.max_step < 1 or self.chips_per_replica < 1:
            raise ValueError(
                f"need max_step >= 1 and chips_per_replica >= 1, got "
                f"{self.max_step}, {self.chips_per_replica}"
            )
        if self.slots_per_replica < 1:
            raise ValueError(
                f"need slots_per_replica >= 1, got {self.slots_per_replica}"
            )


@dataclass(frozen=True)
class FleetSnapshot:
    """One evaluation's inputs, assembled by the autoscaler from its
    watch mirror: ``replicas`` is the live backend count (managed +
    static), ``busy`` the fleet-wide active slots + queued requests,
    ``capacity`` the fleet-wide slot total.  Backends that have not
    published load yet contribute ``slots_per_replica`` of capacity
    and zero busy — a booting replica must dilute utilization, not
    spike it."""

    replicas: int
    busy: float
    capacity: float

    @property
    def utilization(self) -> float:
        if self.capacity > 0:
            return self.busy / self.capacity
        return float("inf") if self.busy > 0 else 0.0


@dataclass(frozen=True)
class Decision:
    direction: str | None  # SCALE_OUT / SCALE_IN / None
    count: int
    utilization: float
    reason: str


def decide(policy: AutoscalePolicy, snapshot: FleetSnapshot) -> Decision:
    """The band decision for one evaluation — pure: no clocks, no
    cooldowns (PolicyState gates those), no actuation."""
    util = snapshot.utilization
    replicas = snapshot.replicas
    # Floor/ceiling enforcement precedes the band: an empty fleet must
    # bootstrap to min_replicas with no traffic at all, and a fleet
    # above max (an operator added static backends) sheds managed
    # replicas regardless of load.
    if replicas < policy.min_replicas:
        return Decision(
            SCALE_OUT,
            min(policy.max_step, policy.min_replicas - replicas),
            util,
            f"fleet below min_replicas={policy.min_replicas}",
        )
    if replicas > policy.max_replicas:
        return Decision(
            SCALE_IN,
            min(policy.max_step, replicas - policy.max_replicas),
            util,
            f"fleet above max_replicas={policy.max_replicas}",
        )
    if util > policy.high_watermark:
        want = min(policy.max_step, policy.max_replicas - replicas)
        if want <= 0:
            return Decision(
                None, 0, util,
                f"utilization {util:.2f} > {policy.high_watermark} but "
                f"already at max_replicas={policy.max_replicas}",
            )
        return Decision(
            SCALE_OUT, want, util,
            f"utilization {util:.2f} > {policy.high_watermark}",
        )
    if util < policy.low_watermark and replicas > policy.min_replicas:
        # Largest step whose projected post-removal utilization stays
        # strictly inside the band (anti-flap projection).
        count = min(policy.max_step, replicas - policy.min_replicas)
        while count > 0:
            remaining = snapshot.capacity - count * policy.slots_per_replica
            if remaining > 0 and (
                snapshot.busy / remaining < policy.high_watermark
            ):
                return Decision(
                    SCALE_IN, count, util,
                    f"utilization {util:.2f} < {policy.low_watermark}",
                )
            count -= 1
        return Decision(
            None, 0, util,
            f"utilization {util:.2f} < {policy.low_watermark} but any "
            f"removal would project past the {policy.high_watermark} "
            "high watermark",
        )
    return Decision(None, 0, util, "inside the band")


def decide_pools(
    policies: dict[str, AutoscalePolicy],
    snapshots: dict[str, FleetSnapshot],
) -> dict[str, Decision]:
    """Per-pool band decisions for a disaggregated fleet (ISSUE 12):
    each pool — prefill, decode — evaluates its OWN watermarks against
    its OWN members' utilization, so the two replica counts move
    independently (a prompt-heavy hour grows the prefill pool while
    decode holds, and vice versa).  Pure like :func:`decide`; a pool
    with no snapshot evaluates empty (bootstrap to min_replicas).  The
    autoscaler holds per-pool :class:`PolicyState` cooldowns beside
    these."""
    empty = FleetSnapshot(replicas=0, busy=0.0, capacity=0.0)
    return {
        pool: decide(policy, snapshots.get(pool, empty))
        for pool, policy in policies.items()
    }


class PolicyState:
    """The time-dependent half of the policy: per-direction cooldowns
    and the ENOSPC backoff.  Every method takes an explicit ``now``
    (the autoscaler's injectable clock) so the boundary tests are
    exact, not sleep-based."""

    def __init__(self, policy: AutoscalePolicy):
        self.policy = policy
        self._last: dict[str, float | None] = {SCALE_OUT: None, SCALE_IN: None}
        self._backoff_until: float | None = None

    def cooldown_blocks(self, direction: str, now: float) -> bool:
        last = self._last[direction]
        if last is None:
            return False
        cooldown = (
            self.policy.scale_out_cooldown_s
            if direction == SCALE_OUT
            else self.policy.scale_in_cooldown_s
        )
        # Blocked strictly inside the window; the expiry instant is
        # allowed (the cooldown-edge test pins this).
        return now - last < cooldown

    def enospc_blocks(self, now: float) -> bool:
        return self._backoff_until is not None and now < self._backoff_until

    def note_action(self, direction: str, now: float) -> None:
        self._last[direction] = now
        if direction == SCALE_OUT:
            # A successful scale-out proves the pool has room again.
            self._backoff_until = None

    def note_enospc(self, now: float) -> None:
        self._backoff_until = now + self.policy.enospc_backoff_s
