"""CSI Controller service: volume provisioning.

≙ reference pkg/oim-csi-driver/controllerserver.go: CreateVolume validates
access modes, serializes per volume name, and provisions through the backend
(Malloc BDev there; a pre-provisioned TPU allocation here).  Capacity is
counted in **chips**: ``parameters["chipCount"]`` (StorageClass parameter)
decides the slice size, and ``Volume.capacity_bytes`` reports chips — the
TPU generalization of bytes for a device that is not byte-addressed.
"""

from __future__ import annotations

import grpc

from oim_tpu.controller.keymutex import KeyMutex
from oim_tpu.csi.backend import VolumeError, _parse_chip_count, _parse_membership
from oim_tpu.spec import csi_pb2

SINGLE_NODE_ACCESS_MODES = (
    csi_pb2.VolumeCapability.AccessMode.SINGLE_NODE_WRITER,
    csi_pb2.VolumeCapability.AccessMode.SINGLE_NODE_READER_ONLY,
)
# A multi-host slice is staged on every member host by design, which in CSI
# terms is a multi-node volume.
MULTI_NODE_ACCESS_MODES = SINGLE_NODE_ACCESS_MODES + (
    csi_pb2.VolumeCapability.AccessMode.MULTI_NODE_READER_ONLY,
    csi_pb2.VolumeCapability.AccessMode.MULTI_NODE_MULTI_WRITER,
)


def _allowed_modes(params: dict):
    num_hosts, _ = _parse_membership(params)
    return MULTI_NODE_ACCESS_MODES if num_hosts > 1 else SINGLE_NODE_ACCESS_MODES


def validate_capabilities(capabilities, params: dict, context) -> None:
    if not capabilities:
        context.abort(
            grpc.StatusCode.INVALID_ARGUMENT, "volume_capabilities required"
        )
    allowed = _allowed_modes(params)
    for cap in capabilities:
        if cap.access_mode.mode not in allowed:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "a single-host TPU slice attaches to one node; access mode "
                f"{cap.access_mode.mode} unsupported",
            )


class ControllerServer:
    def __init__(self, backend, driver_name: str, controller_id: str = "") -> None:
        self.backend = backend
        self.driver_name = driver_name
        self.controller_id = controller_id
        # Per-volume-name serialization (≙ volumeNameMutex,
        # reference serialize.go:13-16, controllerserver.go:56).
        self._mutex = KeyMutex()

    def _abort(self, context, exc: VolumeError):
        context.abort(exc.code, exc.message)

    def CreateVolume(self, request, context) -> csi_pb2.CreateVolumeResponse:
        if not request.name:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "name required")
        params = dict(request.parameters)
        validate_capabilities(request.volume_capabilities, params, context)
        try:
            chip_count = _parse_chip_count(params)
            num_hosts, _ = _parse_membership(params)
        except VolumeError as exc:
            context.abort(exc.code, exc.message)
        if request.capacity_range.required_bytes > 0:
            # Orchestrators that size PVCs in "bytes" get 1 chip per unit.
            chip_count = max(chip_count, int(request.capacity_range.required_bytes))
        map_params = getattr(self.backend, "map_params", None)
        if map_params is not None:
            # Emulated foreign driver: the translation hook decides chip
            # count AND topology, and allocation happens at NodeStage
            # where that request is issued (≙ the reference's ceph path,
            # created at MapVolume time, controller.go:280-297).
            # Pre-provisioning a flat chipCount here would conflict with
            # the topology-shaped MapVolume the stage performs.
            try:
                translated = map_params(params)
            except ValueError as exc:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
            provisioned = translated.slice.chip_count
            if int(request.capacity_range.required_bytes) > provisioned:
                # The dialect's topology decides the size; a PVC asking
                # for more must fail HERE, not bind a too-small PV.
                context.abort(
                    grpc.StatusCode.OUT_OF_RANGE,
                    f"requested {request.capacity_range.required_bytes} "
                    f"chips but the translated topology provides "
                    f"{provisioned}",
                )
        else:
            with self._mutex.locked(request.name):
                if num_hosts > 1:
                    # Multi-host slices allocate on-demand on each member
                    # host at NodeStage (≙ the reference's Ceph path,
                    # created at MapVolume time, controller.go:280-297);
                    # pre-provisioning on the one controller this server
                    # happens to route to would reserve chips on the
                    # wrong host.
                    provisioned = chip_count * num_hosts
                else:
                    try:
                        provisioned = self.backend.provision(
                            request.name, chip_count
                        )
                    except VolumeError as exc:
                        self._abort(context, exc)
        response = csi_pb2.CreateVolumeResponse()
        response.volume.volume_id = request.name
        response.volume.capacity_bytes = provisioned
        if map_params is None:
            # volume_context chipCount is what each host's NodeStage maps
            # (per-host chips), not the volume total.  Emulated volumes
            # carry the foreign dialect's own keys instead.
            response.volume.volume_context["chipCount"] = str(
                chip_count if num_hosts > 1 else provisioned
            )
        for key, value in request.parameters.items():
            response.volume.volume_context.setdefault(key, value)
        if self.controller_id:
            topo = response.volume.accessible_topology.add()
            topo.segments[f"{self.driver_name}/controller-id"] = self.controller_id
        return response

    def DeleteVolume(self, request, context) -> csi_pb2.DeleteVolumeResponse:
        if not request.volume_id:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "volume_id required")
        with self._mutex.locked(request.volume_id):
            try:
                self.backend.delete(request.volume_id)
            except VolumeError as exc:
                self._abort(context, exc)
        return csi_pb2.DeleteVolumeResponse()

    def ValidateVolumeCapabilities(
        self, request, context
    ) -> csi_pb2.ValidateVolumeCapabilitiesResponse:
        if not request.volume_id:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "volume_id required")
        if not request.volume_capabilities:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "volume_capabilities required"
            )
        try:
            num_hosts, _ = _parse_membership(dict(request.volume_context))
        except VolumeError:
            # Malformed membership context: treat as the single-host default
            # for both the existence check and the allowed-modes check below.
            num_hosts = 1
        if num_hosts <= 1 and getattr(self.backend, "map_params", None) is None:
            # Multi-host AND emulated volumes allocate at NodeStage (see
            # CreateVolume) — this controller has no backend state to
            # consult for them, so the CSI NOT_FOUND check applies only
            # to single-host native volumes.
            try:
                exists = self.backend.volume_exists(request.volume_id)
            except VolumeError as exc:
                self._abort(context, exc)
            if not exists:
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"volume {request.volume_id!r} does not exist",
                )
        response = csi_pb2.ValidateVolumeCapabilitiesResponse()
        allowed = (
            MULTI_NODE_ACCESS_MODES if num_hosts > 1 else SINGLE_NODE_ACCESS_MODES
        )
        for cap in request.volume_capabilities:
            if cap.access_mode.mode not in allowed:
                response.message = (
                    f"access mode {cap.access_mode.mode} unsupported"
                )
                return response
        response.confirmed.volume_capabilities.extend(request.volume_capabilities)
        return response

    def GetCapacity(self, request, context) -> csi_pb2.GetCapacityResponse:
        try:
            free = self.backend.capacity()
        except VolumeError as exc:
            self._abort(context, exc)
        return csi_pb2.GetCapacityResponse(available_capacity=free)

    # ListVolumes pagination tokens: "n:<volume_id>" = resume after that
    # name.  Name-based (not index-based) so a volume deleted between pages
    # cannot shift later entries out of the listing.
    _TOKEN_PREFIX = "n:"

    def ListVolumes(self, request, context) -> csi_pb2.ListVolumesResponse:
        """Allocations as CSI volumes, with CSI-standard token pagination
        (the reference declared LIST_VOLUMES but returned UNIMPLEMENTED,
        controllerserver.go:161)."""
        if request.max_entries < 0:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "max_entries must be >= 0"
            )
        after = ""
        if request.starting_token:
            if not request.starting_token.startswith(self._TOKEN_PREFIX):
                context.abort(
                    grpc.StatusCode.ABORTED,
                    f"invalid starting_token {request.starting_token!r}",
                )
            after = request.starting_token[len(self._TOKEN_PREFIX):]
        try:
            volumes = sorted(
                self.backend.list_volumes(), key=lambda v: v["name"]
            )
        except VolumeError as exc:
            self._abort(context, exc)
        remaining = [v for v in volumes if v["name"] > after]
        end = (
            min(request.max_entries, len(remaining))
            if request.max_entries
            else len(remaining)
        )
        response = csi_pb2.ListVolumesResponse()
        for vol in remaining[:end]:
            entry = response.entries.add()
            entry.volume.volume_id = vol["name"]
            entry.volume.capacity_bytes = vol["chip_count"]
            entry.volume.volume_context["chipCount"] = str(vol["chip_count"])
        if end < len(remaining):
            response.next_token = self._TOKEN_PREFIX + remaining[end - 1]["name"]
        return response

    def ControllerGetCapabilities(
        self, request, context
    ) -> csi_pb2.ControllerGetCapabilitiesResponse:
        response = csi_pb2.ControllerGetCapabilitiesResponse()
        for rpc_type in (
            csi_pb2.ControllerServiceCapability.RPC.CREATE_DELETE_VOLUME,
            csi_pb2.ControllerServiceCapability.RPC.LIST_VOLUMES,
            csi_pb2.ControllerServiceCapability.RPC.GET_CAPACITY,
        ):
            cap = response.capabilities.add()
            cap.rpc.type = rpc_type
        return response
