"""CSI Controller service: volume provisioning.

≙ reference pkg/oim-csi-driver/controllerserver.go: CreateVolume validates
access modes, serializes per volume name, and provisions through the backend
(Malloc BDev there; a pre-provisioned TPU allocation here).  Capacity is
counted in **chips**: ``parameters["chipCount"]`` (StorageClass parameter)
decides the slice size, and ``Volume.capacity_bytes`` reports chips — the
TPU generalization of bytes for a device that is not byte-addressed.
"""

from __future__ import annotations

import grpc

from oim_tpu.controller.keymutex import KeyMutex
from oim_tpu.csi.backend import VolumeError, _parse_chip_count
from oim_tpu.spec import csi_pb2

SUPPORTED_ACCESS_MODES = (
    csi_pb2.VolumeCapability.AccessMode.SINGLE_NODE_WRITER,
    csi_pb2.VolumeCapability.AccessMode.SINGLE_NODE_READER_ONLY,
)


def validate_capabilities(capabilities, context) -> None:
    if not capabilities:
        context.abort(
            grpc.StatusCode.INVALID_ARGUMENT, "volume_capabilities required"
        )
    for cap in capabilities:
        if cap.access_mode.mode not in SUPPORTED_ACCESS_MODES:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "a TPU slice attaches to a single node; access mode "
                f"{cap.access_mode.mode} unsupported",
            )


class ControllerServer:
    def __init__(self, backend, driver_name: str, controller_id: str = "") -> None:
        self.backend = backend
        self.driver_name = driver_name
        self.controller_id = controller_id
        # Per-volume-name serialization (≙ volumeNameMutex,
        # reference serialize.go:13-16, controllerserver.go:56).
        self._mutex = KeyMutex()

    def _abort(self, context, exc: VolumeError):
        context.abort(exc.code, exc.message)

    def CreateVolume(self, request, context) -> csi_pb2.CreateVolumeResponse:
        if not request.name:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "name required")
        validate_capabilities(request.volume_capabilities, context)
        try:
            chip_count = _parse_chip_count(dict(request.parameters))
        except VolumeError as exc:
            context.abort(exc.code, exc.message)
        if request.capacity_range.required_bytes > 0:
            # Orchestrators that size PVCs in "bytes" get 1 chip per unit.
            chip_count = max(chip_count, int(request.capacity_range.required_bytes))
        with self._mutex.locked(request.name):
            try:
                provisioned = self.backend.provision(request.name, chip_count)
            except VolumeError as exc:
                self._abort(context, exc)
        response = csi_pb2.CreateVolumeResponse()
        response.volume.volume_id = request.name
        response.volume.capacity_bytes = provisioned
        response.volume.volume_context["chipCount"] = str(provisioned)
        for key, value in request.parameters.items():
            response.volume.volume_context.setdefault(key, value)
        if self.controller_id:
            topo = response.volume.accessible_topology.add()
            topo.segments[f"{self.driver_name}/controller-id"] = self.controller_id
        return response

    def DeleteVolume(self, request, context) -> csi_pb2.DeleteVolumeResponse:
        if not request.volume_id:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "volume_id required")
        with self._mutex.locked(request.volume_id):
            try:
                self.backend.delete(request.volume_id)
            except VolumeError as exc:
                self._abort(context, exc)
        return csi_pb2.DeleteVolumeResponse()

    def ValidateVolumeCapabilities(
        self, request, context
    ) -> csi_pb2.ValidateVolumeCapabilitiesResponse:
        if not request.volume_id:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "volume_id required")
        response = csi_pb2.ValidateVolumeCapabilitiesResponse()
        for cap in request.volume_capabilities:
            if cap.access_mode.mode not in SUPPORTED_ACCESS_MODES:
                response.message = (
                    f"access mode {cap.access_mode.mode} unsupported"
                )
                return response
        response.confirmed.volume_capabilities.extend(request.volume_capabilities)
        return response

    def GetCapacity(self, request, context) -> csi_pb2.GetCapacityResponse:
        try:
            free = self.backend.capacity()
        except VolumeError as exc:
            self._abort(context, exc)
        return csi_pb2.GetCapacityResponse(available_capacity=free)

    def ControllerGetCapabilities(
        self, request, context
    ) -> csi_pb2.ControllerGetCapabilitiesResponse:
        response = csi_pb2.ControllerGetCapabilitiesResponse()
        for rpc_type in (
            csi_pb2.ControllerServiceCapability.RPC.CREATE_DELETE_VOLUME,
            csi_pb2.ControllerServiceCapability.RPC.GET_CAPACITY,
        ):
            cap = response.capabilities.add()
            cap.rpc.type = rpc_type
        return response
