"""Third-party CSI-driver emulation hooks.

≙ the reference's ceph-csi masquerade (reference
pkg/oim-csi-driver/oim-driver.go:80-99, ceph-csi.go:33-107): the OIM driver
can serve under a foreign driver's name and translate that driver's
NodeStage volume attributes into a ``MapVolumeRequest`` via a per-driver
registered translation function, so existing StorageClasses keep working.

Built-in: ``gke-tpu`` translating device-plugin-style attributes
(``google.com/tpu-count``/``google.com/tpu-topology``) into SliceParams.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from oim_tpu.spec import oim_pb2

MapVolumeParams = Callable[[dict], oim_pb2.MapVolumeRequest]


@dataclass
class EmulatedDriver:
    name: str
    map_volume_params: MapVolumeParams


_EMULATED: dict[str, EmulatedDriver] = {}


def register_emulated_driver(name: str, fn: MapVolumeParams) -> None:
    """≙ ``EmulateCSI0Driver`` registration (oim-driver.go:96-99)."""
    _EMULATED[name] = EmulatedDriver(name, fn)


def emulated_driver(name: str) -> EmulatedDriver | None:
    return _EMULATED.get(name)


def _gke_tpu_params(params: dict) -> oim_pb2.MapVolumeRequest:
    request = oim_pb2.MapVolumeRequest()
    topology_spec = params.get("google.com/tpu-topology", "")
    count = int(params.get("google.com/tpu-count", "0") or "0")
    dims = [int(d) for d in topology_spec.split("x") if d] if topology_spec else []
    if dims:
        product = 1
        for d in dims:
            product *= d
        if count and count != product:
            # Contradictory parameters must fail where the hook first
            # runs (CreateVolume), not strand the pod in
            # ContainerCreating when every NodeStage hits the agent's
            # product check.
            raise ValueError(
                f"google.com/tpu-count {count} contradicts topology "
                f"{topology_spec} ({product} chips)"
            )
        count = product
    if not count:
        raise ValueError(
            "gke-tpu emulation requires google.com/tpu-count or "
            "google.com/tpu-topology"
        )
    request.slice.chip_count = count
    if dims:
        request.slice.topology.dims.extend(dims)
    accel = params.get("google.com/tpu-accelerator", "")
    if accel:
        request.slice.accel_type = accel
    return request


register_emulated_driver("gke-tpu", _gke_tpu_params)
