"""Staging/publishing of TPU devices into pod filesystems.

The capability mirror of the reference's vendored ``pkg/mount`` (k8s mount
utils + SafeFormatAndMount): where a block device gets formatted and mounted
(reference pkg/oim-csi-driver/nodeserver.go:204-207), a TPU volume gets its
device files linked into the staging directory together with a
``tpu-bootstrap.json`` the workload reads to initialize JAX, and publish
bind-mounts (or symlinks, in rootless mode) staging → target.

``Exec`` is injectable (≙ ``mount.FakeExec``, reference pkg/mount/exec.go:
35-50) so tests can observe mount commands without privileges.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
from typing import Callable

from oim_tpu import log
from oim_tpu.csi import procmounts

BOOTSTRAP_FILE = "tpu-bootstrap.json"

Exec = Callable[[list[str]], subprocess.CompletedProcess]


def os_exec(argv: list[str]) -> subprocess.CompletedProcess:
    return subprocess.run(argv, capture_output=True, text=True)


class Mounter:
    """Default rootless implementation: symlinks for devices, copy-tree for
    publish.  ``BindMounter`` below uses real bind mounts when privileged."""

    def __init__(self, exec_fn: Exec = os_exec) -> None:
        self.exec_fn = exec_fn

    # -- staging -----------------------------------------------------------

    def stage(self, staging_dir: str, bootstrap: dict) -> None:
        """Write the bootstrap file and link each chip's device file."""
        os.makedirs(staging_dir, exist_ok=True)
        with open(os.path.join(staging_dir, BOOTSTRAP_FILE), "w") as f:
            json.dump(bootstrap, f, indent=2, sort_keys=True)
        for chip in bootstrap.get("chips", []):
            link = os.path.join(staging_dir, os.path.basename(chip["device_path"]))
            if os.path.islink(link) or os.path.exists(link):
                continue
            os.symlink(chip["device_path"], link)
        log.current().info(
            "staged TPU volume",
            staging_dir=staging_dir,
            chips=len(bootstrap.get("chips", [])),
        )

    def is_staged(self, staging_dir: str) -> bool:
        return os.path.exists(os.path.join(staging_dir, BOOTSTRAP_FILE))

    def unstage(self, staging_dir: str) -> None:
        if os.path.isdir(staging_dir):
            for entry in os.listdir(staging_dir):
                path = os.path.join(staging_dir, entry)
                if os.path.islink(path) or os.path.isfile(path):
                    os.unlink(path)

    # -- publishing --------------------------------------------------------

    def publish(self, staging_dir: str, target_dir: str, readonly: bool) -> None:
        os.makedirs(target_dir, exist_ok=True)
        for entry in os.listdir(staging_dir):
            src = os.path.join(staging_dir, entry)
            dst = os.path.join(target_dir, entry)
            if os.path.exists(dst) or os.path.islink(dst):
                continue
            if os.path.islink(src):
                os.symlink(os.readlink(src), dst)
            else:
                shutil.copy2(src, dst)
                if readonly:
                    os.chmod(dst, 0o444)

    def is_published(self, target_dir: str) -> bool:
        return os.path.exists(os.path.join(target_dir, BOOTSTRAP_FILE))

    def unpublish(self, target_dir: str) -> None:
        if os.path.isdir(target_dir):
            for entry in os.listdir(target_dir):
                path = os.path.join(target_dir, entry)
                if os.path.islink(path) or os.path.isfile(path):
                    os.unlink(path)


class BindMounter(Mounter):
    """Privileged variant publishing via ``mount --bind`` (the deployment
    DaemonSet runs privileged with mount propagation, like the reference's
    malloc-daemonset.yaml)."""

    def publish(self, staging_dir: str, target_dir: str, readonly: bool) -> None:
        os.makedirs(target_dir, exist_ok=True)
        argv = ["mount", "--bind", staging_dir, target_dir]
        result = self.exec_fn(argv)
        if result.returncode != 0:
            raise RuntimeError(f"bind mount failed: {result.stderr}")
        if readonly:
            result = self.exec_fn(
                ["mount", "-o", "remount,ro,bind", target_dir]
            )
            if result.returncode != 0:
                raise RuntimeError(f"ro remount failed: {result.stderr}")

    def unpublish(self, target_dir: str) -> None:
        if self.is_published(target_dir):
            result = self.exec_fn(["umount", target_dir])
            if result.returncode != 0:
                raise RuntimeError(f"umount failed: {result.stderr}")

    def is_published(self, target_dir: str) -> bool:
        # The mount table, not os.path.ismount: a bind mount within one
        # filesystem (this driver's publish pattern) has the same st_dev
        # as its parent and the heuristic misses it (procmounts.py).
        return procmounts.is_mount_point(target_dir)
