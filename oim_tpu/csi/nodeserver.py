"""CSI Node service: stage/publish TPU volumes into pods.

≙ reference pkg/oim-csi-driver/nodeserver.go:

- ``NodeStageVolume`` (:149-210) maps the volume through the backend (the
  path the north-star metric times), waits for the chip device files (the
  ``waitForDevice`` analog) and stages them + the JAX bootstrap config into
  the staging directory — where the reference ran SafeFormatAndMount, this
  driver materializes what a JAX process needs to initialize on the slice.
- ``NodePublishVolume`` (:43-120) binds staging → pod target.
- Unstage/Unpublish are idempotent teardowns; unstage also unmaps the
  volume through the backend.
"""

from __future__ import annotations

import time

import grpc

from oim_tpu import log
from oim_tpu.common import events
from oim_tpu.controller.keymutex import KeyMutex
from oim_tpu.csi.backend import VolumeError, wait_for_devices
from oim_tpu.csi.mounter import Mounter
from oim_tpu.spec import csi_pb2

DEFAULT_DEVICE_TIMEOUT = 60.0

_COMPONENT = "oim-csi-driver"


class NodeServer:
    def __init__(
        self,
        backend,
        node_id: str,
        driver_name: str,
        mounter: Mounter | None = None,
        controller_id: str = "",
        device_timeout: float = DEFAULT_DEVICE_TIMEOUT,
    ) -> None:
        self.backend = backend
        self.node_id = node_id
        self.driver_name = driver_name
        self.mounter = mounter or Mounter()
        self.controller_id = controller_id
        self.device_timeout = device_timeout
        self._mutex = KeyMutex()

    def NodeStageVolume(self, request, context) -> csi_pb2.NodeStageVolumeResponse:
        if not request.volume_id:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "volume_id required")
        if not request.staging_target_path:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "staging_target_path required"
            )
        if not request.HasField("volume_capability"):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "volume_capability required"
            )
        with self._mutex.locked(request.volume_id):
            if self.mounter.is_staged(request.staging_target_path):
                return csi_pb2.NodeStageVolumeResponse()  # idempotent
            # Lifecycle clock: stage begin opens the volume's e2e window
            # (closed by NodePublish); the map and stage phases feed
            # oim_volume_lifecycle_seconds and the event timeline.
            events.begin_e2e(request.volume_id)
            staged_ok = False
            try:
                with events.phase(request.volume_id, "stage", _COMPONENT):
                    # Respect the caller's deadline like the reference's
                    # ctx-cancellation-aware device wait
                    # (oim-driver_test.go:209-226) — for both the
                    # multi-host rendezvous inside create_device and the
                    # device wait.
                    remaining = context.time_remaining()
                    deadline = (
                        time.monotonic() + remaining - 1.0
                        if remaining is not None
                        else None
                    )
                    with events.phase(request.volume_id, "map", _COMPONENT):
                        staged = self.backend.create_device(
                            request.volume_id,
                            dict(request.volume_context),
                            deadline,
                        )
                    timeout = self.device_timeout
                    if remaining is not None:
                        timeout = min(timeout, max(remaining - 1.0, 0.1))
                    wait_for_devices(
                        [chip["device_path"] for chip in staged.chips], timeout
                    )
                    self.mounter.stage(
                        request.staging_target_path, staged.bootstrap()
                    )
                staged_ok = True
            except VolumeError as exc:
                context.abort(exc.code, exc.message)
            finally:
                # ANY failed stage abandons the e2e window — a mounter
                # OSError (not just VolumeError) must not strand an
                # entry in the bounded start table, where it could later
                # evict a live flow's clock.
                if not staged_ok:
                    events.abandon_e2e(request.volume_id)
        log.current().info(
            "NodeStageVolume done",
            volume=request.volume_id,
            staging=request.staging_target_path,
        )
        return csi_pb2.NodeStageVolumeResponse()

    def NodeUnstageVolume(self, request, context) -> csi_pb2.NodeUnstageVolumeResponse:
        if not request.volume_id or not request.staging_target_path:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "volume_id and staging_target_path required",
            )
        with self._mutex.locked(request.volume_id):
            events.abandon_e2e(request.volume_id)
            self.mounter.unstage(request.staging_target_path)
            try:
                self.backend.destroy_device(request.volume_id)
            except VolumeError as exc:
                context.abort(exc.code, exc.message)
        events.emit(
            "volume.unstage", component=_COMPONENT, subject=request.volume_id
        )
        return csi_pb2.NodeUnstageVolumeResponse()

    def NodePublishVolume(self, request, context) -> csi_pb2.NodePublishVolumeResponse:
        if not request.volume_id:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "volume_id required")
        if not request.target_path:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "target_path required")
        if not request.staging_target_path:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "staging_target_path required"
            )
        with self._mutex.locked(request.volume_id):
            if self.mounter.is_published(request.target_path):
                return csi_pb2.NodePublishVolumeResponse()  # idempotent
            if not self.mounter.is_staged(request.staging_target_path):
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"volume {request.volume_id!r} is not staged at "
                    f"{request.staging_target_path!r}",
                )
            with events.phase(request.volume_id, "publish", _COMPONENT):
                self.mounter.publish(
                    request.staging_target_path,
                    request.target_path,
                    request.readonly,
                )
            # Publish completes the map→stage→publish flow: close the
            # e2e window opened at stage begin.
            events.end_e2e(request.volume_id, _COMPONENT)
        return csi_pb2.NodePublishVolumeResponse()

    def NodeUnpublishVolume(
        self, request, context
    ) -> csi_pb2.NodeUnpublishVolumeResponse:
        if not request.volume_id or not request.target_path:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "volume_id and target_path required",
            )
        with self._mutex.locked(request.volume_id):
            self.mounter.unpublish(request.target_path)
        events.emit(
            "volume.unpublish", component=_COMPONENT, subject=request.volume_id
        )
        return csi_pb2.NodeUnpublishVolumeResponse()

    def NodeGetCapabilities(
        self, request, context
    ) -> csi_pb2.NodeGetCapabilitiesResponse:
        response = csi_pb2.NodeGetCapabilitiesResponse()
        cap = response.capabilities.add()
        cap.rpc.type = csi_pb2.NodeServiceCapability.RPC.STAGE_UNSTAGE_VOLUME
        return response

    def NodeGetInfo(self, request, context) -> csi_pb2.NodeGetInfoResponse:
        response = csi_pb2.NodeGetInfoResponse(node_id=self.node_id)
        if self.controller_id:
            response.accessible_topology.segments[
                f"{self.driver_name}/controller-id"
            ] = self.controller_id
        return response
