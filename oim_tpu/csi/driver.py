"""Driver assembly: options → servicers → CSI endpoint.

≙ reference pkg/oim-csi-driver/oim-driver.go: functional options choose
exactly one of local mode (agent socket) or remote mode (registry +
controller ID), enforced the way the reference does
(oim-driver.go:216-226); ``emulate`` switches on a foreign driver's
parameter translation (oim-driver.go:80-99).
"""

from __future__ import annotations

from typing import Callable

from oim_tpu.common import metrics, tracing
from oim_tpu.common.interceptors import LogServerInterceptor
from oim_tpu.common.server import NonBlockingGRPCServer
from oim_tpu.common.tlsconfig import TLSConfig
from oim_tpu.csi.backend import LocalBackend, RemoteBackend
from oim_tpu.csi.controllerserver import ControllerServer
from oim_tpu.csi.emulation import emulated_driver
from oim_tpu.csi.identityserver import IdentityServer
from oim_tpu.csi.legacy import ControllerServer0, IdentityServer0, NodeServer0
from oim_tpu.csi.mounter import Mounter
from oim_tpu.csi.nodeserver import NodeServer
from oim_tpu.spec import (
    CSI0_CONTROLLER,
    CSI0_IDENTITY,
    CSI0_NODE,
    CSI_CONTROLLER,
    CSI_IDENTITY,
    CSI_NODE,
)

DEFAULT_DRIVER_NAME = "tpu.oim.io"
CSI_VERSIONS = ("1.0", "0.3")


class OIMDriver:
    def __init__(
        self,
        csi_endpoint: str,
        node_id: str = "node-0",
        driver_name: str = DEFAULT_DRIVER_NAME,
        agent_socket: str = "",
        registry_address: str = "",
        controller_id: str = "",
        tls_loader: Callable[[], TLSConfig] | None = None,
        emulate: str = "",
        mounter: Mounter | None = None,
        device_timeout: float = 60.0,
        rendezvous_timeout: float = 60.0,
        csi_versions: tuple[str, ...] = CSI_VERSIONS,
    ) -> None:
        local = bool(agent_socket)
        remote = bool(registry_address)
        if local == remote:
            raise ValueError(
                "exactly one of agent_socket (local mode) or "
                "registry_address (remote mode) must be set"
            )
        if remote and not controller_id:
            raise ValueError("remote mode requires controller_id")

        map_params = None
        if emulate:
            driver = emulated_driver(emulate)
            if driver is None:
                raise ValueError(f"unknown emulated driver {emulate!r}")
            driver_name = driver.name
            map_params = driver.map_volume_params

        if local:
            if map_params is not None:
                raise ValueError("emulation requires remote mode")
            self.backend = LocalBackend(agent_socket)
        else:
            self.backend = RemoteBackend(
                registry_address,
                controller_id,
                tls_loader=tls_loader,
                map_params=map_params,
                rendezvous_timeout=rendezvous_timeout,
            )

        unknown = set(csi_versions) - set(CSI_VERSIONS)
        if unknown or not csi_versions:
            raise ValueError(
                f"csi_versions must be a non-empty subset of {CSI_VERSIONS}"
            )
        self.csi_versions = tuple(csi_versions)
        self.csi_endpoint = csi_endpoint
        self.identity = IdentityServer(
            driver_name, with_topology=bool(controller_id)
        )
        self.controller = ControllerServer(
            self.backend, driver_name, controller_id=controller_id
        )
        self.node = NodeServer(
            self.backend,
            node_id=node_id,
            driver_name=driver_name,
            mounter=mounter,
            controller_id=controller_id,
            device_timeout=device_timeout,
        )

    def start_server(self) -> NonBlockingGRPCServer:
        """CSI endpoints are plain unix sockets guarded by filesystem
        permissions (kubelet convention), so no TLS here — matching the
        reference's CSI socket.

        Both CSI generations can serve from the one socket — the service
        names (``csi.v1.*`` vs ``csi.v0.*``) never collide, so unlike the
        reference (which picks one personality per process,
        oim-driver.go:39-63) old and new kubelets are handled at once.
        """
        registrars = []
        if "1.0" in self.csi_versions:
            registrars += [
                CSI_IDENTITY.registrar(self.identity),
                CSI_CONTROLLER.registrar(self.controller),
                CSI_NODE.registrar(self.node),
            ]
        if "0.3" in self.csi_versions:
            registrars += [
                CSI0_IDENTITY.registrar(IdentityServer0(self.identity)),
                CSI0_CONTROLLER.registrar(ControllerServer0(self.controller)),
                CSI0_NODE.registrar(NodeServer0(self.node)),
            ]
        srv = NonBlockingGRPCServer(
            self.csi_endpoint,
            interceptors=(
                tracing.TraceServerInterceptor("oim-csi-driver"),
                metrics.MetricsServerInterceptor("oim-csi-driver"),
                LogServerInterceptor(),
            ),
        )
        srv.start(*registrars)
        return srv

    def close(self) -> None:
        """Release backend resources (cached channels, agent sockets)."""
        close = getattr(self.backend, "close", None)
        if close is not None:
            close()
