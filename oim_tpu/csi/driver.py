"""Driver assembly: options → servicers → CSI endpoint.

≙ reference pkg/oim-csi-driver/oim-driver.go: functional options choose
exactly one of local mode (agent socket) or remote mode (registry +
controller ID), enforced the way the reference does
(oim-driver.go:216-226); ``emulate`` switches on a foreign driver's
parameter translation (oim-driver.go:80-99).
"""

from __future__ import annotations

from typing import Callable

from oim_tpu.common.interceptors import LogServerInterceptor
from oim_tpu.common.server import NonBlockingGRPCServer
from oim_tpu.common.tlsconfig import TLSConfig
from oim_tpu.csi.backend import LocalBackend, RemoteBackend
from oim_tpu.csi.controllerserver import ControllerServer
from oim_tpu.csi.emulation import emulated_driver
from oim_tpu.csi.identityserver import IdentityServer
from oim_tpu.csi.mounter import Mounter
from oim_tpu.csi.nodeserver import NodeServer
from oim_tpu.spec import CSI_CONTROLLER, CSI_IDENTITY, CSI_NODE

DEFAULT_DRIVER_NAME = "tpu.oim.io"


class OIMDriver:
    def __init__(
        self,
        csi_endpoint: str,
        node_id: str = "node-0",
        driver_name: str = DEFAULT_DRIVER_NAME,
        agent_socket: str = "",
        registry_address: str = "",
        controller_id: str = "",
        tls_loader: Callable[[], TLSConfig] | None = None,
        emulate: str = "",
        mounter: Mounter | None = None,
        device_timeout: float = 60.0,
        rendezvous_timeout: float = 60.0,
    ) -> None:
        local = bool(agent_socket)
        remote = bool(registry_address)
        if local == remote:
            raise ValueError(
                "exactly one of agent_socket (local mode) or "
                "registry_address (remote mode) must be set"
            )
        if remote and not controller_id:
            raise ValueError("remote mode requires controller_id")

        map_params = None
        if emulate:
            driver = emulated_driver(emulate)
            if driver is None:
                raise ValueError(f"unknown emulated driver {emulate!r}")
            driver_name = driver.name
            map_params = driver.map_volume_params

        if local:
            if map_params is not None:
                raise ValueError("emulation requires remote mode")
            self.backend = LocalBackend(agent_socket)
        else:
            self.backend = RemoteBackend(
                registry_address,
                controller_id,
                tls_loader=tls_loader,
                map_params=map_params,
                rendezvous_timeout=rendezvous_timeout,
            )

        self.csi_endpoint = csi_endpoint
        self.identity = IdentityServer(
            driver_name, with_topology=bool(controller_id)
        )
        self.controller = ControllerServer(
            self.backend, driver_name, controller_id=controller_id
        )
        self.node = NodeServer(
            self.backend,
            node_id=node_id,
            driver_name=driver_name,
            mounter=mounter,
            controller_id=controller_id,
            device_timeout=device_timeout,
        )

    def start_server(self) -> NonBlockingGRPCServer:
        """CSI endpoints are plain unix sockets guarded by filesystem
        permissions (kubelet convention), so no TLS here — matching the
        reference's CSI socket."""
        srv = NonBlockingGRPCServer(
            self.csi_endpoint, interceptors=(LogServerInterceptor(),)
        )
        srv.start(
            CSI_IDENTITY.registrar(self.identity),
            CSI_CONTROLLER.registrar(self.controller),
            CSI_NODE.registrar(self.node),
        )
        return srv
