"""CSI driver backends: local (agent socket) vs remote (registry proxy).

≙ the reference's ``OIMBackend`` split (reference
pkg/oim-csi-driver/oim-driver.go:71-78; local.go; remote.go): the same CSI
services drive either the device plane directly (local mode — provisioning
host) or a controller reached through the registry's transparent proxy
(remote mode — compute host whose kernel cannot see the device plane).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable

import grpc

from oim_tpu import log
from oim_tpu.agent import Agent, AgentError, ENODEV, ENOSPC, EEXIST
from oim_tpu.common import endpoint as ep
from oim_tpu.common import pci as pcilib
from oim_tpu.common import events, resilience, tracing
from oim_tpu.common.chancache import ChannelCache, RECONNECT_OPTIONS
from oim_tpu.common.tlsconfig import TLSConfig
from oim_tpu.csi import rendezvous
from oim_tpu.spec import CONTROLLER, REGISTRY, oim_pb2


@dataclass
class StagedDevice:
    """What NodeStage needs to materialize a TPU volume in a pod."""

    volume_id: str
    chips: list[dict] = field(default_factory=list)
    mesh: list[int] = field(default_factory=list)
    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = 0

    def bootstrap(self) -> dict:
        """The tpu-bootstrap.json contents (consumed by
        oim_tpu.parallel.coordinator)."""
        return {
            "volume_id": self.volume_id,
            "chips": self.chips,
            "mesh": self.mesh,
            "coordinator_address": self.coordinator_address,
            "num_processes": self.num_processes,
            "process_id": self.process_id,
        }


class VolumeError(Exception):
    def __init__(self, code: grpc.StatusCode, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


def _parse_int_param(params: dict, key: str, default: int) -> int:
    raw = params.get(key, str(default))
    try:
        value = int(raw)
    except (TypeError, ValueError):
        raise VolumeError(
            grpc.StatusCode.INVALID_ARGUMENT, f"invalid {key} {raw!r}"
        ) from None
    if value < 0:
        raise VolumeError(
            grpc.StatusCode.INVALID_ARGUMENT, f"invalid {key} {raw!r}"
        )
    return value


def _parse_chip_count(params: dict, default: int = 1) -> int:
    return _parse_int_param(params, "chipCount", default)


def _parse_membership(params: dict) -> tuple[int, frozenset[str] | None]:
    """(num_hosts, declared member set or None) from the volume parameters.

    ``hosts`` (comma-separated host ids) declares fixed membership —
    recommended for multi-host volumes since it makes the rendezvous immune
    to stale or foreign registry entries; ``numHosts`` alone allows dynamic
    membership.  Both given must agree.
    """
    members = None
    raw = params.get("hosts", "")
    if raw:
        ids = [h.strip() for h in raw.split(",") if h.strip()]
        if not ids or len(set(ids)) != len(ids):
            raise VolumeError(
                grpc.StatusCode.INVALID_ARGUMENT, f"invalid hosts {raw!r}"
            )
        members = frozenset(ids)
    num_hosts = _parse_int_param(params, "numHosts", 0)
    if members is not None:
        if num_hosts and num_hosts != len(members):
            raise VolumeError(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"numHosts={num_hosts} contradicts hosts list of "
                f"{len(members)}",
            )
        num_hosts = len(members)
    return max(1, num_hosts), members


def wait_for_devices(paths: list[str], timeout: float, poll: float = 0.1) -> None:
    """Block until every device file exists.

    ≙ the reference's ``waitForDevice`` sysfs watch (reference
    pkg/oim-csi-driver/remote.go:249-290): there it waits for virtio-scsi
    hotplug; here for the agent-owned device nodes to appear, polling with a
    deadline (the reference used fsnotify + a 5s rescan tick; a poll loop has
    the same observable behavior for control-plane latencies).
    """
    with tracing.start_span("device/wait", devices=len(paths)):
        deadline = time.monotonic() + timeout
        # ``pjrt:N`` ids (agent --chips-from-pjrt mode) are logical, not
        # filesystem nodes: the PJRT enumeration that produced them already
        # observed the live device, so there is nothing to wait for.
        missing = [p for p in paths if not p.startswith("pjrt:")]
        while missing:
            missing = [p for p in missing if not os.path.exists(p)]
            if not missing:
                return
            if time.monotonic() >= deadline:
                raise VolumeError(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    f"device(s) never appeared: {missing}",
                )
            time.sleep(poll)


def _staged_from_reply(
    volume_id: str, reply: oim_pb2.MapVolumeReply, default_pci: str = ""
) -> StagedDevice:
    """Convert a MapVolumeReply, completing partial PCI addresses from the
    registry default (≙ ``CompletePCIAddress``, remote.go:170-190)."""
    fallback = None
    if default_pci:
        try:
            fallback = pcilib.parse_bdf_string(default_pci)
        except ValueError:
            log.current().warning("invalid registry pci default", value=default_pci)
    chips = []
    for chip in reply.chips:
        addr = pcilib.PCIAddress(
            chip.pci.domain, chip.pci.bus, chip.pci.device, chip.pci.function
        )
        if fallback is not None:
            addr = pcilib.merge(addr, fallback)
        chips.append(
            {
                "chip_id": chip.chip_id,
                "device_path": chip.device_path,
                "pci": str(addr),
                "coord": list(chip.coord.coords),
            }
        )
    return StagedDevice(
        volume_id=volume_id,
        chips=chips,
        mesh=list(reply.mesh.dims),
        coordinator_address=reply.coordinator_address,
        num_processes=reply.num_processes or 1,
        process_id=reply.process_id,
    )


# ---------------------------------------------------------------------------
# Local backend


class LocalBackend:
    """Drives the tpu-agent directly (≙ localSPDK, reference local.go:24-84)."""

    def __init__(self, agent_socket: str) -> None:
        self.agent_socket = agent_socket

    def _agent(self) -> Agent:
        try:
            return Agent(self.agent_socket)
        except OSError as exc:
            raise VolumeError(
                grpc.StatusCode.UNAVAILABLE,
                f"tpu-agent at {self.agent_socket} unavailable: {exc}",
            ) from exc

    def provision(self, volume_id: str, chip_count: int) -> int:
        with self._agent() as agent:
            try:
                alloc = agent.create_allocation(
                    volume_id, chip_count, provisioned=True
                )
            except AgentError as exc:
                code = {
                    ENOSPC: grpc.StatusCode.RESOURCE_EXHAUSTED,
                    EEXIST: grpc.StatusCode.ALREADY_EXISTS,
                }.get(exc.code, grpc.StatusCode.INTERNAL)
                raise VolumeError(code, str(exc)) from exc
            if not alloc["provisioned"]:
                raise VolumeError(
                    grpc.StatusCode.ALREADY_EXISTS,
                    f"{volume_id!r} is in use by an on-demand allocation",
                )
            return alloc["chip_count"]

    def delete(self, volume_id: str) -> None:
        with self._agent() as agent:
            alloc = agent.find_allocation(volume_id)
            if alloc is None:
                return
            try:
                if alloc["attached"]:
                    agent.detach_allocation(volume_id)
                agent.delete_allocation(volume_id)
            except AgentError as exc:
                if exc.code != ENODEV:
                    raise VolumeError(grpc.StatusCode.INTERNAL, str(exc)) from exc

    def capacity(self) -> int:
        with self._agent() as agent:
            return agent.get_topology()["free_chips"]

    def list_volumes(self) -> list[dict]:
        with self._agent() as agent:
            return [
                {"name": a["name"], "chip_count": a["chip_count"]}
                for a in agent.get_allocations()
            ]

    def volume_exists(self, volume_id: str) -> bool:
        """Any allocation counts — a statically provisioned volume staged
        on demand (provisioned=False) still exists for CSI purposes."""
        with self._agent() as agent:
            return agent.find_allocation(volume_id) is not None

    def create_device(
        self, volume_id: str, params: dict, deadline: float | None = None
    ) -> StagedDevice:
        with self._agent() as agent:
            alloc = agent.find_allocation(volume_id)
            if alloc is None:
                chip_count = _parse_chip_count(params)
                try:
                    agent.create_allocation(volume_id, chip_count)
                except AgentError as exc:
                    code = {
                        ENOSPC: grpc.StatusCode.RESOURCE_EXHAUSTED
                    }.get(exc.code, grpc.StatusCode.INTERNAL)
                    raise VolumeError(code, str(exc)) from exc
            attached = agent.attach_allocation(volume_id)
        staged = StagedDevice(
            volume_id=volume_id,
            chips=[
                {
                    "chip_id": c["chip_id"],
                    "device_path": c["device_path"],
                    "pci": c["pci"],
                    "coord": c["coord"],
                }
                for c in attached["chips"]
            ],
            mesh=attached["mesh"],
            coordinator_address=(
                f"127.0.0.1:{attached['coordinator_port']}"
                if attached.get("coordinator_port")
                else ""
            ),
        )
        return staged

    def destroy_device(self, volume_id: str) -> None:
        with self._agent() as agent:
            alloc = agent.find_allocation(volume_id)
            if alloc is None:
                return
            if alloc["attached"]:
                agent.detach_allocation(volume_id)
            if not alloc["provisioned"]:
                agent.delete_allocation(volume_id)


# ---------------------------------------------------------------------------
# Remote backend


class RemoteBackend:
    """Routes through the registry proxy to a controller (≙ remoteSPDK,
    reference remote.go:33-42).

    TLS material is (re)loaded through ``tls_loader`` on every call, so
    rotated keys are picked up without a restart (≙ remote.go:101-114) —
    but the *channel* is reused while the material and target stay
    unchanged (oim_tpu.common.chancache), dropping the reference's
    per-call TCP+TLS handshake from the NodeStage hot path.
    """

    def __init__(
        self,
        registry_address: str,
        controller_id: str,
        tls_loader: Callable[[], TLSConfig] | None = None,
        map_params: Callable[[dict], oim_pb2.MapVolumeRequest] | None = None,
        rendezvous_timeout: float = 60.0,
        retry: resilience.RetryPolicy | None = None,
        breaker: resilience.CircuitBreaker | None = None,
    ) -> None:
        self.registry_address = registry_address
        self.controller_id = controller_id
        self.tls_loader = tls_loader
        self.map_params = map_params
        # Multi-host rendezvous identity: one controller per host, so the
        # controller id doubles as the host id (it is also what the host's
        # TLS CN ``host.<id>`` pins, so the registry authz lines up).
        self.rendezvous_timeout = rendezvous_timeout
        self._channels = ChannelCache()
        # Proxy-hop resilience: bounded retries (safe — controller
        # map/unmap are volume_id-keyed idempotent) plus a breaker so a
        # dead registry/controller gets probed, not hammered.  Retrying
        # MapVolume can double-allocate ONLY if the controller forgot the
        # first success; the idempotency cache there is what makes this
        # policy sound.
        self.retry = retry if retry is not None else resilience.RetryPolicy.from_env()
        self.breaker = (
            breaker
            if breaker is not None
            else resilience.CircuitBreaker.from_env(
                f"{controller_id}@{registry_address}"
            )
        )

        # Rendezvous channel factory: cache-backed, so rendezvous must not
        # close what it yields (see rendezvous.join's ownership contract).
        def registry_factory():
            return self._channel()

        registry_factory.owns_channels = True
        self._registry_factory = registry_factory

    def _channel(self) -> grpc.Channel:
        # A restarted registry at the same address is handled by gRPC's
        # own reconnect (bounded by RECONNECT_OPTIONS); rotated TLS
        # material or a changed address re-dials via the fingerprint.
        target = ep.parse(self.registry_address).grpc_target()
        if self.tls_loader is not None:
            tls = self.tls_loader().with_peer("component.registry")
            return self._channels.get(
                "registry",
                (target, tls.ca_pem, tls.cert_pem, tls.key_pem),
                lambda: tracing.trace_channel(
                    grpc.secure_channel(
                        target,
                        tls.channel_credentials(),
                        options=tls.channel_options() + RECONNECT_OPTIONS,
                    ),
                    "oim-csi-driver",
                ),
            )
        return self._channels.get(
            "registry",
            (target, None),
            lambda: tracing.trace_channel(
                grpc.insecure_channel(target, options=RECONNECT_OPTIONS),
                "oim-csi-driver",
            ),
        )

    def _metadata(self) -> tuple:
        # Proxy routing key (≙ remote.go:78).
        return (("controllerid", self.controller_id),)

    def _call(self, fn, op: str = "call"):
        """Run ``fn(channel, attempt)`` under the shared retry policy +
        breaker; ``attempt.clamped(...)`` gives each RPC a timeout that
        respects the ladder's remaining overall-deadline budget.

        On UNAVAILABLE the cached registry channel is invalidated before
        the re-attempt, so the retry re-dials instead of reusing a dead
        cached channel (gRPC's own reconnect can lag a registry restart
        at a *new* address — the fingerprint only changes when the TLS
        material or target does).
        """

        def attempt(att):
            return fn(self._channel(), att)

        def on_retry(exc: BaseException, _attempt: int) -> None:
            if resilience.status_of(exc) == grpc.StatusCode.UNAVAILABLE:
                self._channels.invalidate("registry")

        try:
            return resilience.call_with_retry(
                attempt,
                self.retry,
                component="oim-csi-driver",
                op=op,
                breaker=self.breaker,
                on_retry=on_retry,
            )
        except grpc.RpcError as exc:
            # status_of/details_of default a locally raised RpcError's
            # None code/details safely instead of crashing CSI formatting.
            raise VolumeError(
                resilience.status_of(exc), resilience.details_of(exc)
            ) from exc
        except resilience.BreakerOpenError as exc:
            raise VolumeError(grpc.StatusCode.UNAVAILABLE, str(exc)) from exc

    def close(self) -> None:
        self._channels.close()

    def provision(self, volume_id: str, chip_count: int) -> int:
        def run(channel, attempt):
            stub = CONTROLLER.stub(channel)
            clamp = attempt.budget_clamp(self.retry.clock)
            stub.ProvisionSlice(
                oim_pb2.ProvisionSliceRequest(name=volume_id, chip_count=chip_count),
                metadata=self._metadata(),
                timeout=clamp(30.0),
            )
            return stub.CheckSlice(
                oim_pb2.CheckSliceRequest(name=volume_id),
                metadata=self._metadata(),
                timeout=clamp(30.0),
            ).chip_count

        return self._call(run, op="ProvisionSlice")

    def delete(self, volume_id: str) -> None:
        def run(channel, attempt):
            CONTROLLER.stub(channel).ProvisionSlice(
                oim_pb2.ProvisionSliceRequest(name=volume_id, chip_count=0),
                metadata=self._metadata(),
                timeout=attempt.clamped(default=30.0),
            )

        self._call(run, op="DeleteSlice")

    def capacity(self) -> int:
        """Free chips on the mapped controller's device plane, through the
        proxy (the reference left remote capacity UNIMPLEMENTED;
        ≙ controllerserver.go:150-159 + this repo's GetTopology RPC)."""
        def run(channel, attempt):
            return CONTROLLER.stub(channel).GetTopology(
                oim_pb2.GetTopologyRequest(),
                metadata=self._metadata(),
                timeout=attempt.clamped(default=30.0),
            ).free_chips

        return self._call(run, op="GetTopology")

    def list_volumes(self) -> list[dict]:
        def run(channel, attempt):
            reply = CONTROLLER.stub(channel).ListSlices(
                oim_pb2.ListSlicesRequest(),
                metadata=self._metadata(),
                timeout=attempt.clamped(default=30.0),
            )
            return [
                {"name": s.name, "chip_count": s.chip_count}
                for s in reply.slices
            ]

        return self._call(run, op="ListSlices")

    def volume_exists(self, volume_id: str) -> bool:
        def run(channel, attempt):
            try:
                CONTROLLER.stub(channel).CheckSlice(
                    oim_pb2.CheckSliceRequest(
                        name=volume_id, include_unprovisioned=True
                    ),
                    metadata=self._metadata(),
                    timeout=attempt.clamped(default=30.0),
                )
                return True
            except grpc.RpcError as exc:
                if exc.code() == grpc.StatusCode.NOT_FOUND:
                    return False
                raise

        return self._call(run, op="CheckSlice")

    def _check_not_evicted(
        self, channel, volume_id: str, timeout: float = 30.0
    ) -> None:
        """Refuse to stage a volume the fault-management loop has marked
        evicted (oim_tpu/health): FAILED_PRECONDITION until an operator
        remaps it (``oimctl remap``) — staging onto a faulted slice would
        hand the workload dead chips."""
        from oim_tpu.health import states as health_states

        path = health_states.eviction_key(volume_id)
        reply = REGISTRY.stub(channel).GetValues(
            oim_pb2.GetValuesRequest(path=path), timeout=timeout
        )
        for value in reply.values:
            if value.path == path and value.value:
                events.emit(
                    "volume.stage.refused-evicted",
                    component="oim-csi-driver",
                    severity=events.WARNING,
                    subject=volume_id,
                    controller=self.controller_id,
                    eviction=value.value,
                )
                raise VolumeError(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"volume {volume_id!r} is evicted ({value.value}); "
                    "remap it with `oimctl remap` before staging",
                )

    def default_pci(self, channel, timeout: float = 30.0) -> str:
        """Registry-stored PCI default for this controller
        (≙ remote.go:129-145)."""
        reply = REGISTRY.stub(channel).GetValues(
            oim_pb2.GetValuesRequest(path=f"{self.controller_id}/pci"),
            timeout=timeout,
        )
        for value in reply.values:
            if value.path == f"{self.controller_id}/pci":
                return value.value
        return ""

    def create_device(
        self, volume_id: str, params: dict, deadline: float | None = None
    ) -> StagedDevice:
        def run(channel, attempt):
            clamp = attempt.budget_clamp(self.retry.clock)
            self._check_not_evicted(channel, volume_id, clamp(30.0))
            default_pci = self.default_pci(channel, clamp(30.0))
            if self.map_params is not None:
                # Emulation hook: translate a foreign driver's parameters
                # (≙ emulation via MapVolumeParams, remote.go:156-164).
                try:
                    request = self.map_params(params)
                except ValueError as exc:
                    raise VolumeError(
                        grpc.StatusCode.INVALID_ARGUMENT, str(exc)
                    ) from exc
                request.volume_id = volume_id
            else:
                request = oim_pb2.MapVolumeRequest(volume_id=volume_id)
                chip_count = _parse_chip_count(params, default=0)
                if chip_count > 0:
                    request.slice.chip_count = chip_count
                else:
                    request.provisioned.SetInParent()
            reply = CONTROLLER.stub(channel).MapVolume(
                request,
                metadata=self._metadata(),
                timeout=clamp(60.0),
            )
            return _staged_from_reply(volume_id, reply, default_pci)

        staged = self._call(run, op="MapVolume")
        num_hosts, members = _parse_membership(params)
        if num_hosts > 1:
            # Converge with the volume's other hosts on one coordinator and
            # a stable process-id assignment (oim_tpu/csi/rendezvous.py).
            if not staged.coordinator_address:
                raise VolumeError(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"volume {volume_id!r}: controller returned no "
                    "coordinator candidate for a multi-host volume",
                )
            timeout = self.rendezvous_timeout
            if deadline is not None:
                # Respect the CSI call's own deadline, like the device wait
                # (≙ oim-driver_test.go:209-226's ctx-cancellation check).
                timeout = min(timeout, max(deadline - time.monotonic(), 0.1))
            try:
                with tracing.start_span(
                    "rendezvous/join", volume=volume_id, num_hosts=num_hosts
                ):
                    placement = rendezvous.join(
                        self._registry_factory,
                        volume_id,
                        self.controller_id,
                        staged.coordinator_address,
                        num_hosts,
                        timeout=timeout,
                        members=members,
                    )
            except rendezvous.RendezvousError as exc:
                raise VolumeError(exc.code, exc.message) from exc
            staged.num_processes = placement.num_processes
            staged.process_id = placement.process_id
            staged.coordinator_address = placement.coordinator_address
        return staged

    def destroy_device(self, volume_id: str) -> None:
        def run(channel, attempt):
            CONTROLLER.stub(channel).UnmapVolume(
                oim_pb2.UnmapVolumeRequest(volume_id=volume_id),
                metadata=self._metadata(),
                timeout=attempt.clamped(default=60.0),
            )

        self._call(run, op="UnmapVolume")
        rendezvous.withdraw(
            self._registry_factory, volume_id, self.controller_id
        )
