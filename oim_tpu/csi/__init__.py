"""CSI driver: the orchestrator-facing surface (≙ reference pkg/oim-csi-driver).

Serves the CSI v1 Identity/Controller/Node services with two backend
personalities — **local** (drives the tpu-agent socket directly) and
**remote** (routes through the registry's transparent proxy to a controller)
— plus emulation hooks translating third-party drivers' volume parameters.
"""

from oim_tpu.csi.driver import OIMDriver
from oim_tpu.csi.backend import LocalBackend, RemoteBackend, StagedDevice

__all__ = ["OIMDriver", "LocalBackend", "RemoteBackend", "StagedDevice"]
