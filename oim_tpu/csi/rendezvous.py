"""Multi-host slice rendezvous over the registry KV.

The reference never had N cooperating node agents per volume; a multi-host
TPU slice does: one NodeStage per host must converge on ONE JAX distributed
coordinator and a stable process-id assignment before any workload starts
(SURVEY.md §7 "Multi-host coordination").  The registry KV is the natural
rendezvous point — it is already the only cluster-wide store, it is reachable
from every node agent, and its CommonName authorization extends naturally:
``host.<h>`` may publish only its own ``volumes/<vid>/hosts/<h>`` key
(≙ the reference letting ``controller.<id>`` set only ``<id>/address``,
reference pkg/oim-registry/registry.go:100-109).

Protocol (driver-side only; no controller/proto changes):

1. Each host maps the volume against its *local* controller, obtaining a
   host-reachable coordinator candidate ``host:port``
   (``MapVolumeReply.coordinator_address``).
2. It publishes ``volumes/<volume_id>/hosts/<host_id> = host:port`` and
   polls ``GetValues(volumes/<volume_id>/hosts)`` until ``num_hosts``
   distinct entries exist (deadline-bounded, like the reference's
   ``waitForDevice`` wait, remote.go:249-290).
3. Host ids are sorted lexicographically; a host's process_id is its sort
   index.  Host ids are stable identities (the controller id), so the *set*
   of ids — and therefore the process-id assignment — is race-free even when
   values are being overwritten.
4. The coordinator is *committed*, not inferred: the sort-first host writes
   ``volumes/<vid>/coordinator = <its own candidate>`` only after seeing all
   ``num_hosts`` entries; every other host accepts the commit only when it
   equals the sort-first host's current entry.  Both keys are written by the
   same writer in order against the linearizable KV, so a peer can never
   observe a fresh entry with a stale commit (or vice versa) from an
   interrupted earlier stage of the same volume.
5. NodeUnstage withdraws the host's key (SetValue of "" deletes,
   ≙ reference registry.go:84-98).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import grpc

from oim_tpu import log
from oim_tpu.spec import REGISTRY, oim_pb2

VOLUMES_PREFIX = "volumes"


class RendezvousError(Exception):
    def __init__(self, code: grpc.StatusCode, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


@dataclass
class Placement:
    """One host's place in a converged multi-host volume."""

    num_processes: int
    process_id: int
    coordinator_address: str


def hosts_path(volume_id: str, host_id: str = "") -> str:
    base = f"{VOLUMES_PREFIX}/{volume_id}/hosts"
    return f"{base}/{host_id}" if host_id else base


def coordinator_path(volume_id: str) -> str:
    return f"{VOLUMES_PREFIX}/{volume_id}/coordinator"


def _set(channel: grpc.Channel, path: str, value: str) -> None:
    REGISTRY.stub(channel).SetValue(
        oim_pb2.SetValueRequest(value=oim_pb2.Value(path=path, value=value)),
        timeout=30,
    )


def publish(channel: grpc.Channel, volume_id: str, host_id: str, endpoint: str) -> None:
    """Publish (or with ``endpoint=""`` withdraw) this host's coordinator
    candidate."""
    _set(channel, hosts_path(volume_id, host_id), endpoint)


def snapshot(channel: grpc.Channel, volume_id: str) -> tuple[dict[str, str], str]:
    """One consistent read of the volume's rendezvous state:
    (``host_id -> candidate`` map, committed coordinator or "")."""
    reply = REGISTRY.stub(channel).GetValues(
        oim_pb2.GetValuesRequest(path=f"{VOLUMES_PREFIX}/{volume_id}"),
        timeout=30,
    )
    hosts: dict[str, str] = {}
    commit = ""
    for value in reply.values:
        parts = value.path.split("/")
        if len(parts) == 4 and parts[2] == "hosts" and value.value:
            hosts[parts[3]] = value.value
        elif len(parts) == 3 and parts[2] == "coordinator":
            commit = value.value
    return hosts, commit


# gRPC codes worth retrying inside the deadline; anything else (e.g.
# INVALID_ARGUMENT from path sanitation, PERMISSION_DENIED from a CN
# mismatch) is permanent and must surface immediately.
_RETRYABLE = frozenset(
    {
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
        grpc.StatusCode.ABORTED,
        grpc.StatusCode.UNKNOWN,
        grpc.StatusCode.INTERNAL,
    }
)


def join(
    channel_factory,
    volume_id: str,
    host_id: str,
    endpoint: str,
    num_hosts: int,
    timeout: float,
    poll: float = 0.2,
    members: frozenset[str] | None = None,
) -> Placement:
    """Publish this host's candidate and wait for all ``num_hosts`` peers.

    ``channel_factory`` yields a registry channel per iteration.  A plain
    factory's channels are closed here after each iteration (per-call
    connections, ≙ reference remote.go:101-114); a factory that manages
    its own channels (oim_tpu.common.chancache) marks itself with
    ``owns_channels = True`` and relies on gRPC reconnect (bounded by
    chancache.RECONNECT_OPTIONS) across registry restarts — either way
    rendezvous survives a restart mid-wait, and the publish re-runs
    every iteration so a restarted in-memory registry is repopulated,
    not just re-dialed.

    ``members``, when given (the volume's declared ``hosts`` parameter),
    fixes the membership: foreign or stale entries from hosts outside the
    set are ignored rather than counted, so a replaced node or a
    misbehaving peer cannot wedge the volume.
    """
    if num_hosts < 1:
        raise RendezvousError(
            grpc.StatusCode.INVALID_ARGUMENT, f"num_hosts={num_hosts} invalid"
        )
    if not host_id:
        raise RendezvousError(
            grpc.StatusCode.INVALID_ARGUMENT,
            "multi-host volume requires a host_id",
        )
    if members is not None and host_id not in members:
        raise RendezvousError(
            grpc.StatusCode.FAILED_PRECONDITION,
            f"host {host_id!r} is not in the volume's declared hosts "
            f"{sorted(members)}",
        )
    factory_owns = getattr(channel_factory, "owns_channels", False)
    deadline = time.monotonic() + timeout
    cleared_stale = committed = False
    coordinator = ""
    hosts: dict[str, str] = {}
    while True:
        channel = channel_factory()
        try:
            if not cleared_stale:
                # A crashed earlier stage can leave a self-consistent
                # (entry, commit) pair behind.  If our allocation changed
                # (different endpoint than our recorded entry), that commit
                # is genuinely stale — clear it before publishing so no
                # peer converges on the dead coordinator.  An unchanged
                # endpoint means attach was idempotent and the old commit
                # is still correct (single-host rejoin keeps working).
                stale_hosts, stale_commit = snapshot(channel, volume_id)
                own = stale_hosts.get(host_id, "")
                if own and own != endpoint and stale_commit:
                    _set(channel, coordinator_path(volume_id), "")
                cleared_stale = True
            # Idempotent overwrite, re-run every iteration.
            publish(channel, volume_id, host_id, endpoint)
            hosts, commit = snapshot(channel, volume_id)
            if members is not None:
                hosts = {h: e for h, e in hosts.items() if h in members}
            order = sorted(hosts)
            if len(order) > num_hosts:
                raise RendezvousError(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"volume {volume_id!r}: {len(order)} hosts registered "
                    f"for a {num_hosts}-host volume: {order}",
                )
            if len(order) == num_hosts and host_id in hosts:
                if order[0] == host_id:
                    # Sort-first host commits its OWN candidate — it knows
                    # it authoritatively, so no read-of-possibly-stale-value
                    # is involved.
                    if not committed:
                        _set(channel, coordinator_path(volume_id), endpoint)
                        committed = True
                    coordinator = endpoint
                    break
                if commit and commit == hosts[order[0]]:
                    # Commit matches the leader's current entry: both were
                    # written, in order, by the same (current) stage.
                    coordinator = commit
                    break
        except grpc.RpcError as exc:
            if exc.code() not in _RETRYABLE:
                raise RendezvousError(
                    exc.code(),
                    f"volume {volume_id!r}: registry rejected rendezvous: "
                    f"{exc.details()}",
                ) from exc
            # Transient registry unavailability must not abort the stage;
            # the deadline bounds total waiting.
            log.current().warning(
                "rendezvous registry error",
                volume=volume_id,
                error=exc.code().name,
            )
        finally:
            if not factory_owns:
                channel.close()
        if time.monotonic() >= deadline:
            raise RendezvousError(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                f"volume {volume_id!r}: {len(hosts)}/{num_hosts} hosts after "
                f"{timeout:.0f}s: {sorted(hosts)}",
            )
        time.sleep(poll)
    placement = Placement(
        num_processes=num_hosts,
        process_id=sorted(hosts).index(host_id),
        coordinator_address=coordinator,
    )
    log.current().info(
        "multi-host rendezvous converged",
        volume=volume_id,
        process=f"{placement.process_id}/{placement.num_processes}",
        coordinator=placement.coordinator_address,
    )
    return placement


def withdraw(channel_factory, volume_id: str, host_id: str) -> None:
    """Remove this host's key on unstage; the last host out also clears the
    committed coordinator so the volume leaves no KV rows behind.
    Best-effort (the volume may already be gone, or the registry briefly
    down — a later stage overwrites whatever remains).  Factories marked
    ``owns_channels`` keep their channel; plain factories' are closed."""
    if not host_id:
        return
    channel = channel_factory()
    try:
        publish(channel, volume_id, host_id, "")
        remaining, commit = snapshot(channel, volume_id)
        if not remaining and commit:
            _set(channel, coordinator_path(volume_id), "")
    except grpc.RpcError as exc:
        log.current().warning(
            "rendezvous withdraw failed", volume=volume_id, error=exc.code().name
        )
    finally:
        if not getattr(channel_factory, "owns_channels", False):
            channel.close()
