"""/proc/mounts parsing and mount-point detection.

≙ the mount-table half of the reference's vendored k8s mount utils
(reference pkg/mount/mount_linux.go: ``parseProcMounts``,
``IsLikelyNotMountPoint``, ``GetMountRefs``).  The TPU driver has no
filesystems to format, but the privileged BindMounter still needs a
truthful "is this target mounted" answer: ``os.path.ismount`` (like the
reference's ``IsLikelyNotMountPoint``, which it documents as a heuristic)
compares device numbers with the parent and therefore misses bind mounts
within one filesystem — exactly the publish pattern this driver uses.  The
mount table is the authority.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

PROC_MOUNTS = "/proc/mounts"

# /proc/mounts octal-escapes whitespace and backslashes in paths
# (\040 space, \011 tab, \012 newline, \134 backslash).
_ESCAPES = {"040": " ", "011": "\t", "012": "\n", "134": "\\"}


def _unescape(field_text: str) -> str:
    out = []
    i = 0
    while i < len(field_text):
        ch = field_text[i]
        if ch == "\\" and field_text[i + 1 : i + 4] in _ESCAPES:
            out.append(_ESCAPES[field_text[i + 1 : i + 4]])
            i += 4
        else:
            out.append(ch)
            i += 1
    return "".join(out)


@dataclass
class MountPoint:
    device: str
    path: str
    fstype: str
    opts: list[str] = field(default_factory=list)
    freq: int = 0
    passno: int = 0


def parse_mounts(content: str) -> list[MountPoint]:
    """Parse /proc/mounts content (6 whitespace-separated fields per line,
    octal-escaped; ≙ parseProcMounts, reference mount_linux.go)."""
    mounts = []
    for line in content.splitlines():
        parts = line.split()
        if len(parts) != 6:
            continue  # kernel guarantees 6; skip anything malformed
        mounts.append(
            MountPoint(
                device=_unescape(parts[0]),
                path=_unescape(parts[1]),
                fstype=parts[2],
                opts=parts[3].split(","),
                freq=int(parts[4]),
                passno=int(parts[5]),
            )
        )
    return mounts


def list_mounts(proc_mounts: str = PROC_MOUNTS) -> list[MountPoint]:
    try:
        with open(proc_mounts) as f:
            return parse_mounts(f.read())
    except OSError:
        return []


def is_mount_point(path: str, proc_mounts: str = PROC_MOUNTS) -> bool:
    """Authoritative check against the mount table — catches the
    same-filesystem bind mounts ``os.path.ismount`` cannot."""
    real = os.path.realpath(path)
    return any(m.path == real or m.path == path for m in list_mounts(proc_mounts))


def is_likely_not_mount_point(path: str) -> bool:
    """The fast heuristic (≙ IsLikelyNotMountPoint, reference
    mount_linux.go): st_dev comparison with the parent.  False negatives on
    bind mounts; use ``is_mount_point`` when the answer matters."""
    return not os.path.ismount(path)


MOUNTINFO = "/proc/self/mountinfo"


@dataclass
class MountInfoEntry:
    mount_id: int
    parent_id: int
    major_minor: str
    root: str
    path: str
    opts: list[str]
    fstype: str
    source: str


def parse_mountinfo(content: str) -> list[MountInfoEntry]:
    """Parse /proc/self/mountinfo.  Unlike /proc/mounts, each entry carries
    the *root* of the mount within its filesystem — the field that lets a
    bind mount be distinguished from other mounts of the same device
    (≙ the reference's k8s mount utils, which use mountinfo for exactly
    this; see GetMountRefs / SearchMountPoints)."""
    entries = []
    for line in content.splitlines():
        parts = line.split()
        try:
            sep = parts.index("-")
        except ValueError:
            continue
        if sep < 6 or len(parts) < sep + 3:
            continue
        entries.append(
            MountInfoEntry(
                mount_id=int(parts[0]),
                parent_id=int(parts[1]),
                major_minor=parts[2],
                root=_unescape(parts[3]),
                path=_unescape(parts[4]),
                opts=parts[5].split(","),
                fstype=parts[sep + 1],
                source=_unescape(parts[sep + 2]),
            )
        )
    return entries


def list_mountinfo(mountinfo: str = MOUNTINFO) -> list[MountInfoEntry]:
    try:
        with open(mountinfo) as f:
            return parse_mountinfo(f.read())
    except OSError:
        return []


def mount_refs(path: str, mountinfo: str = MOUNTINFO) -> list[str]:
    """Other mount points of the *same filesystem subtree* (≙ GetMountRefs)
    — what an unmounter consults before releasing the underlying resource.
    Matching is by (device, root): a bind mount shares both with its source,
    while unrelated mounts of the same device (e.g. ``/`` when the staging
    dir lives on the root filesystem) differ in root and are not refs."""
    real = os.path.realpath(path)
    entries = list_mountinfo(mountinfo)
    # Overmounts: the kernel lists mounts in order, the *last* entry at a
    # path is the visible one — match against that, not a shadowed mount.
    target = next(
        (e for e in reversed(entries) if e.path in (real, path)), None
    )
    if target is None:
        return []
    return [
        e.path
        for e in entries
        if e.major_minor == target.major_minor
        and e.root == target.root
        and e.path not in (real, path)
    ]
