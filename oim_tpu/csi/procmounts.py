"""/proc/mounts parsing and mount-point detection.

≙ the mount-table half of the reference's vendored k8s mount utils
(reference pkg/mount/mount_linux.go: ``parseProcMounts``,
``IsLikelyNotMountPoint``, ``GetMountRefs``).  The TPU driver has no
filesystems to format, but the privileged BindMounter still needs a
truthful "is this target mounted" answer: ``os.path.ismount`` (like the
reference's ``IsLikelyNotMountPoint``, which it documents as a heuristic)
compares device numbers with the parent and therefore misses bind mounts
within one filesystem — exactly the publish pattern this driver uses.  The
mount table is the authority.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

PROC_MOUNTS = "/proc/mounts"

# /proc/mounts octal-escapes whitespace and backslashes in paths
# (\040 space, \011 tab, \012 newline, \134 backslash).
_ESCAPES = {"040": " ", "011": "\t", "012": "\n", "134": "\\"}


def _unescape(field_text: str) -> str:
    out = []
    i = 0
    while i < len(field_text):
        ch = field_text[i]
        if ch == "\\" and field_text[i + 1 : i + 4] in _ESCAPES:
            out.append(_ESCAPES[field_text[i + 1 : i + 4]])
            i += 4
        else:
            out.append(ch)
            i += 1
    return "".join(out)


@dataclass
class MountPoint:
    device: str
    path: str
    fstype: str
    opts: list[str] = field(default_factory=list)
    freq: int = 0
    passno: int = 0


def parse_mounts(content: str) -> list[MountPoint]:
    """Parse /proc/mounts content (6 whitespace-separated fields per line,
    octal-escaped; ≙ parseProcMounts, reference mount_linux.go)."""
    mounts = []
    for line in content.splitlines():
        parts = line.split()
        if len(parts) != 6:
            continue  # kernel guarantees 6; skip anything malformed
        mounts.append(
            MountPoint(
                device=_unescape(parts[0]),
                path=_unescape(parts[1]),
                fstype=parts[2],
                opts=parts[3].split(","),
                freq=int(parts[4]),
                passno=int(parts[5]),
            )
        )
    return mounts


def list_mounts(proc_mounts: str = PROC_MOUNTS) -> list[MountPoint]:
    try:
        with open(proc_mounts) as f:
            return parse_mounts(f.read())
    except OSError:
        return []


def is_mount_point(path: str, proc_mounts: str = PROC_MOUNTS) -> bool:
    """Authoritative check against the mount table — catches the
    same-filesystem bind mounts ``os.path.ismount`` cannot."""
    real = os.path.realpath(path)
    return any(m.path == real or m.path == path for m in list_mounts(proc_mounts))


def is_likely_not_mount_point(path: str) -> bool:
    """The fast heuristic (≙ IsLikelyNotMountPoint, reference
    mount_linux.go): st_dev comparison with the parent.  False negatives on
    bind mounts; use ``is_mount_point`` when the answer matters."""
    return not os.path.ismount(path)


def mount_refs(path: str, proc_mounts: str = PROC_MOUNTS) -> list[str]:
    """Other mount points backed by the same device (≙ GetMountRefs) —
    what an unmounter consults before releasing the underlying resource."""
    real = os.path.realpath(path)
    mounts = list_mounts(proc_mounts)
    device = next(
        (m.device for m in mounts if m.path in (real, path)), None
    )
    if device is None:
        return []
    return [m.path for m in mounts if m.device == device and m.path not in (real, path)]
