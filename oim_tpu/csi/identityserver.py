"""CSI Identity service (≙ reference pkg/oim-csi-driver/identityserver.go)."""

from __future__ import annotations

from oim_tpu.spec import csi_pb2

import oim_tpu


class IdentityServer:
    def __init__(
        self,
        driver_name: str,
        with_controller: bool = True,
        with_topology: bool = False,
    ) -> None:
        self.driver_name = driver_name
        self.with_controller = with_controller
        # Only advertised when NodeGetInfo actually reports topology
        # segments (remote mode with a controller id).
        self.with_topology = with_topology

    def GetPluginInfo(self, request, context) -> csi_pb2.GetPluginInfoResponse:
        return csi_pb2.GetPluginInfoResponse(
            name=self.driver_name, vendor_version=oim_tpu.__version__
        )

    def GetPluginCapabilities(
        self, request, context
    ) -> csi_pb2.GetPluginCapabilitiesResponse:
        response = csi_pb2.GetPluginCapabilitiesResponse()
        if self.with_controller:
            cap = response.capabilities.add()
            cap.service.type = (
                csi_pb2.PluginCapability.Service.CONTROLLER_SERVICE
            )
        if self.with_topology:
            cap = response.capabilities.add()
            cap.service.type = (
                csi_pb2.PluginCapability.Service.VOLUME_ACCESSIBILITY_CONSTRAINTS
            )
        return response

    def Probe(self, request, context) -> csi_pb2.ProbeResponse:
        response = csi_pb2.ProbeResponse()
        response.ready.value = True
        return response
